"""GGIPNN train/eval harness.

Re-design of ``src/GGIPNN_Classification.py``: transductive vocab over all
splits, one-hot labels, Adam(1e-3), train loop with periodic dev evaluation
and checkpointing, then a single-pass test inference producing softmax
scores; ROC-AUC computed from the positive-class column
(``scores[:, 1]``, SURVEY §2.2 #11).

TPU shape vs the reference:

* the per-batch ``sess.run`` feed-dict boundary becomes one donated jitted
  train step; the thrice-repeated test-time ``sess.run`` per batch
  (``src/GGIPNN_Classification.py:238-244``) collapses into one jitted call
  returning scores and predictions together;
* ``embed_train=False`` freezes the table via a masked optimizer (zero
  updates) instead of TF's trainable=False variable flag;
* evaluation pads the final ragged batch to keep shapes static — XLA
  compiles each (batch, seq) shape once.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from gene2vec_tpu.config import GGIPNNConfig
from gene2vec_tpu.eval.metrics import roc_auc_score
from gene2vec_tpu.io.emb_io import load_embedding_for_vocab
from gene2vec_tpu.models.ggipnn import GGIPNN, loss_fn
from gene2vec_tpu.models.ggipnn_data import (
    PairTextVocab,
    batch_iter,
    one_hot_labels,
    read_lines,
)


class GGIPNNTrainer:
    """Trains a :class:`GGIPNN` on encoded (N, 2) id pairs + one-hot labels."""

    def __init__(self, config: GGIPNNConfig, vocab: PairTextVocab):
        self.config = config
        self.vocab = vocab
        self.model = GGIPNN.from_config(config, vocab_size=len(vocab))
        label = "frozen" if not config.embed_train else "train"
        self.tx = optax.multi_transform(
            {
                "train": optax.adam(config.learning_rate),
                "frozen": optax.set_to_zero(),
            },
            param_labels=functools.partial(self._labels, label),
        )
        self._step = 0

    @staticmethod
    def _labels(embedding_label: str, params) -> dict:
        return jax.tree_util.tree_map_with_path(
            lambda path, _: embedding_label
            if any(getattr(p, "key", None) == "embedding" for p in path)
            else "train",
            params,
        )

    # -- setup -------------------------------------------------------------

    def init_state(
        self, pretrained_emb_path: Optional[str] = None
    ) -> Tuple[dict, optax.OptState]:
        key = jax.random.PRNGKey(self.config.seed)
        dummy = jnp.zeros((1, self.config.sequence_length), jnp.int32)
        params = self.model.init({"params": key}, dummy)["params"]
        if pretrained_emb_path is not None and self.config.use_pretrained:
            table = load_embedding_for_vocab(
                self.vocab.token_to_id,
                pretrained_emb_path,
                self.config.embedding_dim,
                rng=np.random.RandomState(self.config.seed),
            )
            params = dict(params)
            params["embedding"] = jnp.asarray(table)
        opt_state = self.tx.init(params)
        return params, opt_state

    # -- jitted steps ------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
    def train_step(self, params, opt_state, batch_x, batch_y, dropout_key):
        def loss_of(p):
            logits = self.model.apply(
                {"params": p}, batch_x, train=True, rngs={"dropout": dropout_key}
            )
            return loss_fn(logits, batch_y, p, self.config.l2_lambda)

        (loss, acc), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, acc

    @functools.partial(jax.jit, static_argnums=0)
    def eval_step(self, params, batch_x, batch_y):
        logits = self.model.apply({"params": params}, batch_x, train=False)
        loss, acc = loss_fn(logits, batch_y, params, self.config.l2_lambda)
        scores = jax.nn.softmax(logits)
        return loss, acc, scores, jnp.argmax(logits, -1)

    # -- loops -------------------------------------------------------------

    def fit(
        self,
        x_train: np.ndarray,
        y_train: np.ndarray,
        x_valid: Optional[np.ndarray] = None,
        y_valid: Optional[np.ndarray] = None,
        log: Callable[[str], None] = print,
        checkpoint_fn: Optional[Callable[[int, dict], None]] = None,
    ) -> Tuple[dict, optax.OptState]:
        cfg = self.config
        params, opt_state = getattr(self, "_state", (None, None))
        if params is None:
            params, opt_state = self.init_state()
        key = jax.random.PRNGKey(cfg.seed + 1)
        stacked = np.concatenate([x_train, y_train], axis=1)
        nx = x_train.shape[1]
        for batch in batch_iter(stacked, cfg.batch_size, cfg.num_epochs, seed=cfg.seed):
            bx = jnp.asarray(batch[:, :nx].astype(np.int32))
            by = jnp.asarray(batch[:, nx:].astype(np.float32))
            key, sub = jax.random.split(key)
            params, opt_state, loss, acc = self.train_step(
                params, opt_state, bx, by, sub
            )
            self._step += 1
            if self._step % cfg.evaluate_every == 0:
                msg = f"step {self._step}: loss {float(loss):.4f} acc {float(acc):.4f}"
                if x_valid is not None and y_valid is not None:
                    dev = self.evaluate(params, x_valid, y_valid)
                    msg += (
                        f" | dev loss {dev['loss']:.4f} acc {dev['accuracy']:.4f}"
                    )
                log(msg)
            if checkpoint_fn is not None and self._step % cfg.checkpoint_every == 0:
                checkpoint_fn(self._step, params)
        self._state = (params, opt_state)
        return params, opt_state

    def evaluate(
        self, params, x: np.ndarray, y_onehot: np.ndarray
    ) -> Dict[str, float]:
        """Full-split evaluation in static-shape batches; returns loss,
        accuracy, and (when both classes present) ROC-AUC from
        ``scores[:, 1]``."""
        scores, preds, losses = self.predict(params, x, y_onehot)
        labels = np.argmax(y_onehot, axis=1)
        out = {
            "loss": float(np.mean(losses)),
            "accuracy": float((preds == labels).mean()),
        }
        if len(np.unique(labels)) == 2:
            out["auc"] = roc_auc_score(labels, scores[:, 1])
        return out

    def predict(
        self, params, x: np.ndarray, y_onehot: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(softmax scores, argmax predictions, per-batch losses) over a
        split, batched at config.batch_size with tail padding."""
        cfg = self.config
        n = x.shape[0]
        if y_onehot is None:
            y_onehot = np.zeros((n, cfg.num_classes), np.float32)
        bs = cfg.batch_size
        scores_out: List[np.ndarray] = []
        preds_out: List[np.ndarray] = []
        losses: List[float] = []
        for start in range(0, n, bs):
            bx = x[start : start + bs]
            by = y_onehot[start : start + bs]
            pad = bs - bx.shape[0]
            if pad:
                bx = np.concatenate([bx, np.repeat(bx[-1:], pad, 0)], 0)
                by = np.concatenate([by, np.repeat(by[-1:], pad, 0)], 0)
            loss, _, scores, preds = self.eval_step(
                params, jnp.asarray(bx, jnp.int32), jnp.asarray(by, jnp.float32)
            )
            take = bs - pad
            scores_out.append(np.asarray(scores)[:take])
            preds_out.append(np.asarray(preds)[:take])
            losses.append(float(loss))
        return (
            np.concatenate(scores_out, 0),
            np.concatenate(preds_out, 0),
            np.asarray(losses),
        )


def run_classification(
    data_dir: str,
    emb_path: Optional[str],
    config: GGIPNNConfig = GGIPNNConfig(),
    log: Callable[[str], None] = print,
) -> Dict[str, float]:
    """End-to-end: the reference's main flow
    (``src/GGIPNN_Classification.py:40-254``) over a ``predictionData/``-shaped
    directory (train/valid/test ``_text.txt`` + ``_label.txt``)."""
    splits = {}
    for split in ("train", "valid", "test"):
        splits[split] = (
            read_lines(f"{data_dir}/{split}_text.txt"),
            read_lines(f"{data_dir}/{split}_label.txt"),
        )
    vocab = PairTextVocab().fit(*(text for text, _ in splits.values()))
    log(f"vocab size: {len(vocab)}")

    enc = {
        s: (vocab.transform(text), one_hot_labels(labels, config.num_classes))
        for s, (text, labels) in splits.items()
    }
    trainer = GGIPNNTrainer(config, vocab)
    params, opt_state = trainer.init_state(pretrained_emb_path=emb_path)
    trainer._state = (params, opt_state)
    params, _ = trainer.fit(*enc["train"], *enc["valid"], log=log)
    result = trainer.evaluate(params, *enc["test"])
    log(f"test accuracy: {result['accuracy']:.4f}")
    if "auc" in result:
        log(f"The AUC score is {result['auc']:.6f}")
    return result
