"""GGIPNN train/eval harness.

Re-design of ``src/GGIPNN_Classification.py``: transductive vocab over all
splits, one-hot labels, Adam(1e-3), train loop with periodic dev evaluation
and checkpointing, then a single-pass test inference producing softmax
scores; ROC-AUC computed from the positive-class column
(``scores[:, 1]``, SURVEY §2.2 #11).

TPU shape vs the reference:

* the per-batch ``sess.run`` feed-dict boundary becomes one donated jitted
  train step; the thrice-repeated test-time ``sess.run`` per batch
  (``src/GGIPNN_Classification.py:238-244``) collapses into one jitted call
  returning scores and predictions together;
* ``embed_train=False`` freezes the table via a masked optimizer (zero
  updates) instead of TF's trainable=False variable flag;
* evaluation pads the final ragged batch to keep shapes static — XLA
  compiles each (batch, seq) shape once.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from gene2vec_tpu.config import GGIPNNConfig
from gene2vec_tpu.eval.metrics import roc_auc_score
from gene2vec_tpu.io.emb_io import load_embedding_for_vocab
from gene2vec_tpu.models.ggipnn import GGIPNN, loss_fn
from gene2vec_tpu.models.ggipnn_data import (
    PairTextVocab,
    batch_iter,
    one_hot_labels,
    read_lines,
)


class GGIPNNTrainer:
    """Trains a :class:`GGIPNN` on encoded (N, 2) id pairs + one-hot labels."""

    def __init__(self, config: GGIPNNConfig, vocab: PairTextVocab):
        self.config = config
        self.vocab = vocab
        self.model = GGIPNN.from_config(config, vocab_size=len(vocab))
        label = "frozen" if not config.embed_train else "train"
        self.tx = optax.multi_transform(
            {
                "train": optax.adam(config.learning_rate),
                "frozen": optax.set_to_zero(),
            },
            param_labels=functools.partial(self._labels, label),
        )
        self._step = 0

    @staticmethod
    def _labels(embedding_label: str, params) -> dict:
        return jax.tree_util.tree_map_with_path(
            lambda path, _: embedding_label
            if any(getattr(p, "key", None) == "embedding" for p in path)
            else "train",
            params,
        )

    # -- setup -------------------------------------------------------------

    def init_state(
        self, pretrained_emb_path: Optional[str] = None
    ) -> Tuple[dict, optax.OptState]:
        key = jax.random.PRNGKey(self.config.seed)
        dummy = jnp.zeros((1, self.config.sequence_length), jnp.int32)
        params = self.model.init({"params": key}, dummy)["params"]
        if pretrained_emb_path is not None and self.config.use_pretrained:
            table = load_embedding_for_vocab(
                self.vocab.token_to_id,
                pretrained_emb_path,
                self.config.embedding_dim,
                rng=np.random.RandomState(self.config.seed),
            )
            params = dict(params)
            params["embedding"] = jnp.asarray(table)
        opt_state = self.tx.init(params)
        return params, opt_state

    # -- jitted steps ------------------------------------------------------

    def _train_step_impl(
        self, params, opt_state, batch_x, batch_y, dropout_key,
        with_grads: bool = False,
    ):
        """Forward/grad/optimizer sequence shared by the per-batch and
        scanned-epoch paths."""
        def loss_of(p):
            logits = self.model.apply(
                {"params": p}, batch_x, train=True, rngs={"dropout": dropout_key}
            )
            return loss_fn(logits, batch_y, p, self.config.l2_lambda)

        (loss, acc), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if with_grads:
            return params, opt_state, loss, acc, grads
        return params, opt_state, loss, acc

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
    def train_step(self, params, opt_state, batch_x, batch_y, dropout_key):
        return self._train_step_impl(
            params, opt_state, batch_x, batch_y, dropout_key
        )

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
    def train_step_grads(self, params, opt_state, batch_x, batch_y, dropout_key):
        """train_step that also returns the gradient pytree — the
        observability path (grad histograms/sparsity per step, reference
        ``src/GGIPNN_Classification.py:129-137``)."""
        return self._train_step_impl(
            params, opt_state, batch_x, batch_y, dropout_key, with_grads=True
        )

    @functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(1, 2))
    def _fit_epoch_scanned(self, params, opt_state, x, y, num_batches, key):
        """One whole training epoch as a single device program: random batch
        order over the pre-shuffled data (no per-batch host dispatch — the
        step-loop path measured ~86 ms/step of pure dispatch overhead on a
        remote TPU)."""
        bs = self.config.batch_size
        order_key, drop_key = jax.random.split(key)
        order = jax.random.permutation(order_key, num_batches)

        def body(carry, step):
            params, opt_state = carry
            start = order[step] * bs
            bx = jax.lax.dynamic_slice_in_dim(x, start, bs)
            by = jax.lax.dynamic_slice_in_dim(y, start, bs)
            params, opt_state, loss, acc = self._train_step_impl(
                params, opt_state, bx, by, jax.random.fold_in(drop_key, step)
            )
            return (params, opt_state), (loss, acc)

        (params, opt_state), (losses, accs) = jax.lax.scan(
            body, (params, opt_state), jnp.arange(num_batches)
        )
        return params, opt_state, jnp.mean(losses), jnp.mean(accs)

    def fit_epoch(self, params, opt_state, x, y, key):
        """Public single-epoch scanned fit over pre-encoded (and possibly
        pre-sharded) device arrays — the entry point bench.py and
        __graft_entry__ drive (round-1 advisor: external callers must not
        reach into the private scanned impl).  Returns
        (params, opt_state, mean loss, mean accuracy)."""
        num_batches = int(x.shape[0]) // self.config.batch_size
        if num_batches == 0:
            # scanning zero batches would return NaN loss/accuracy with
            # params untouched — fail loudly instead
            raise ValueError(
                f"{x.shape[0]} examples is fewer than one batch "
                f"(batch_size={self.config.batch_size})"
            )
        return self._fit_epoch_scanned(params, opt_state, x, y, num_batches, key)

    def profile_kernel(
        self, profiler, params, opt_state, batch_x, batch_y,
        name: str = "ggipnn_step",
    ):
        """AOT kernel attribution of one training step
        (``obs/profiler.py``): lower+compile cost and XLA static costs
        under ``name``.  Profiles a fresh jit of the shared step impl —
        same program as :meth:`train_step` minus the donation, which
        changes no cost-analysis number — so the donated production
        entry point's cache is untouched."""
        # deliberately non-donating: AOT-only, never on the train path
        step = jax.jit(self._train_step_impl)  # graftcheck: disable=missing-donate
        key = jax.random.PRNGKey(self.config.seed)
        return profiler.attribute(
            name, step, (params, opt_state, batch_x, batch_y, key)
        )

    # -- loops -------------------------------------------------------------

    def fit(
        self,
        x_train: np.ndarray,
        y_train: np.ndarray,
        x_valid: Optional[np.ndarray] = None,
        y_valid: Optional[np.ndarray] = None,
        log: Callable[[str], None] = print,
        checkpoint_fn: Optional[Callable[[int, dict], None]] = None,
        run=None,
        preempt=None,
        timeline=None,
    ) -> Tuple[dict, optax.OptState]:
        """Train.  With ``run`` (a :class:`~gene2vec_tpu.models.ggipnn_obs.
        GGIPNNRun`) the reference's observed step loop runs regardless of
        ``scan_fit``: per-step train summaries with grad histograms/
        sparsity, dev summaries every ``evaluate_every``, checkpoints every
        ``checkpoint_every`` keeping 5 (``src/GGIPNN_Classification.py:
        129-163,216-222``).

        ``preempt`` (a resilience ``PreemptionHandler``) drains the step
        loop cooperatively: the in-flight step finishes, a final
        checkpoint is forced through ``checkpoint_fn``/``run`` so no
        progress past the last cadence checkpoint is lost, and the
        partially trained state returns (docs/RESILIENCE.md).

        ``timeline`` (an :class:`~gene2vec_tpu.obs.timeline.
        PhaseTimeline`) records per-step host_ingest / dispatch /
        compute phases on the observed step loop; the caller owns the
        flush (run_classification writes it to the run dir)."""
        from gene2vec_tpu.obs.timeline import PhaseTimeline

        tl = timeline if timeline is not None else PhaseTimeline(
            enabled=False
        )
        cfg = self.config
        params, opt_state = getattr(self, "_state", (None, None))
        if params is None:
            params, opt_state = self.init_state()
        if cfg.scan_fit and checkpoint_fn is None and run is None:
            return self._fit_scanned(
                params, opt_state, x_train, y_train, x_valid, y_valid, log,
                preempt=preempt,
            )
        import time

        key = jax.random.PRNGKey(cfg.seed + 1)
        stacked = np.concatenate([x_train, y_train], axis=1)
        nx = x_train.shape[1]
        for batch in batch_iter(stacked, cfg.batch_size, cfg.num_epochs, seed=cfg.seed):
            t0 = time.perf_counter()
            step_no = self._step + 1
            with tl.phase("host_ingest", step=step_no):
                bx = jnp.asarray(batch[:, :nx].astype(np.int32))
                by = jnp.asarray(batch[:, nx:].astype(np.float32))
                key, sub = jax.random.split(key)
            if run is not None:
                with tl.phase("dispatch", step=step_no):
                    params, opt_state, loss, acc, grads = (
                        self.train_step_grads(params, opt_state, bx, by, sub)
                    )
            else:
                with tl.phase("dispatch", step=step_no):
                    params, opt_state, loss, acc = self.train_step(
                        params, opt_state, bx, by, sub
                    )
            self._step += 1
            if run is not None:
                with tl.phase("compute", step=step_no):
                    loss_f, acc_f = float(loss), float(acc)  # blocks the step
                # span-free watchdog feed: per-batch spans would write
                # thousands of records; stalls still surface as events
                run.obs.record_step(
                    "train_step", time.perf_counter() - t0, step=self._step
                )
                run.log_train(self._step, loss_f, acc_f, grads)
            if self._step % cfg.evaluate_every == 0:
                msg = f"step {self._step}: loss {float(loss):.4f} acc {float(acc):.4f}"
                if x_valid is not None and y_valid is not None:
                    dev = self.evaluate(params, x_valid, y_valid)
                    msg += (
                        f" | dev loss {dev['loss']:.4f} acc {dev['accuracy']:.4f}"
                    )
                    if run is not None:
                        run.log_dev(self._step, dev["loss"], dev["accuracy"])
                log(msg)
            if self._step % cfg.checkpoint_every == 0:
                if checkpoint_fn is not None:
                    checkpoint_fn(self._step, params)
                if run is not None:
                    run.checkpoint(self._step, params)
            if preempt is not None and preempt.triggered:
                # drain: force a checkpoint at THIS step (the cadence one
                # may be hundreds of steps back) and stop
                log(f"preemption requested; drained after step {self._step}")
                if self._step % cfg.checkpoint_every != 0:
                    if checkpoint_fn is not None:
                        checkpoint_fn(self._step, params)
                    if run is not None:
                        run.checkpoint(self._step, params)
                break
        self._state = (params, opt_state)
        return params, opt_state

    def _fit_scanned(
        self, params, opt_state, x_train, y_train, x_valid, y_valid, log,
        preempt=None,
    ) -> Tuple[dict, optax.OptState]:
        """Scanned-epoch fast path: per-epoch dev evaluation instead of the
        reference's every-200-steps cadence (set scan_fit=False or pass a
        checkpoint_fn for the step-loop behavior)."""
        cfg = self.config
        n = x_train.shape[0]
        bs = cfg.batch_size
        # host shuffle once; wrap-pad to a batch multiple (the scan needs
        # static shapes; the ragged reference tail becomes duplicated rows)
        rng = np.random.RandomState(cfg.seed)
        order = rng.permutation(n)
        # cyclic resize handles any n, including n < batch_size
        idx = np.resize(order, ((n + bs - 1) // bs) * bs)
        x = jnp.asarray(x_train[idx], jnp.int32)
        y = jnp.asarray(y_train[idx], jnp.float32)
        num_batches = x.shape[0] // bs
        key = jax.random.PRNGKey(cfg.seed + 1)
        for epoch in range(cfg.num_epochs):
            if preempt is not None and preempt.triggered:
                log(f"preemption requested; drained after epoch {epoch}")
                break
            params, opt_state, loss, acc = self._fit_epoch_scanned(
                params, opt_state, x, y, num_batches,
                jax.random.fold_in(key, epoch),
            )
            self._step += num_batches
            msg = (
                f"epoch {epoch + 1}: loss {float(loss):.4f} "
                f"acc {float(acc):.4f}"
            )
            if x_valid is not None and y_valid is not None:
                dev = self.evaluate(params, x_valid, y_valid)
                msg += f" | dev loss {dev['loss']:.4f} acc {dev['accuracy']:.4f}"
            log(msg)
        self._state = (params, opt_state)
        return params, opt_state

    def evaluate(
        self, params, x: np.ndarray, y_onehot: np.ndarray
    ) -> Dict[str, float]:
        """Full-split evaluation in static-shape batches; returns loss,
        accuracy, and (when both classes present) ROC-AUC from
        ``scores[:, 1]``."""
        scores, preds, losses = self.predict(params, x, y_onehot)
        labels = np.argmax(y_onehot, axis=1)
        out = {
            "loss": float(np.mean(losses)),
            "accuracy": float((preds == labels).mean()),
        }
        if len(np.unique(labels)) == 2:
            out["auc"] = roc_auc_score(labels, scores[:, 1])
        return out

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _predict_scanned(self, params, xy, num_batches):
        bs = self.config.batch_size
        x, y = xy

        def body(_, step):
            bx = jax.lax.dynamic_slice_in_dim(x, step * bs, bs)
            by = jax.lax.dynamic_slice_in_dim(y, step * bs, bs)
            logits = self.model.apply({"params": params}, bx, train=False)
            loss, _ = loss_fn(logits, by, params, self.config.l2_lambda)
            return None, (jax.nn.softmax(logits), jnp.argmax(logits, -1), loss)

        _, (scores, preds, losses) = jax.lax.scan(
            body, None, jnp.arange(num_batches)
        )
        return scores, preds, losses

    def predict(
        self, params, x: np.ndarray, y_onehot: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(softmax scores, argmax predictions, per-batch losses) over a
        split — one jitted scan over padded static-shape batches (the
        reference re-ran ``sess.run`` three times per batch,
        ``src/GGIPNN_Classification.py:238-244``)."""
        cfg = self.config
        n = x.shape[0]
        if y_onehot is None:
            y_onehot = np.zeros((n, cfg.num_classes), np.float32)
        bs = cfg.batch_size
        pad = (-n) % bs
        xp = np.concatenate([x, np.repeat(x[-1:], pad, 0)], 0) if pad else x
        yp = (
            np.concatenate([y_onehot, np.repeat(y_onehot[-1:], pad, 0)], 0)
            if pad
            else y_onehot
        )
        num_batches = xp.shape[0] // bs
        scores, preds, losses = self._predict_scanned(
            params,
            (jnp.asarray(xp, jnp.int32), jnp.asarray(yp, jnp.float32)),
            num_batches,
        )
        return (
            np.asarray(scores).reshape(-1, cfg.num_classes)[:n],
            np.asarray(preds).reshape(-1)[:n],
            np.asarray(losses),
        )


def run_classification(
    data_dir: str,
    emb_path: Optional[str],
    config: GGIPNNConfig = GGIPNNConfig(),
    log: Callable[[str], None] = print,
    run_dir: Optional[str] = None,
    preempt=None,
) -> Dict[str, float]:
    """End-to-end: the reference's main flow
    (``src/GGIPNN_Classification.py:40-254``) over a ``predictionData/``-shaped
    directory (train/valid/test ``_text.txt`` + ``_label.txt``).

    With ``run_dir`` the run is fully observed at the reference cadence —
    the step loop replaces the scanned fast path, writing ``summaries/
    {train,dev}`` (loss/accuracy scalars, grad histograms + sparsity) and
    ``checkpoints/model-<step>.npz`` every ``checkpoint_every`` steps,
    keeping 5 — the reference-comparison configuration."""
    splits = {}
    for split in ("train", "valid", "test"):
        splits[split] = (
            read_lines(f"{data_dir}/{split}_text.txt"),
            read_lines(f"{data_dir}/{split}_label.txt"),
        )
    vocab = PairTextVocab().fit(*(text for text, _ in splits.values()))
    log(f"vocab size: {len(vocab)}")

    enc = {
        s: (vocab.transform(text), one_hot_labels(labels, config.num_classes))
        for s, (text, labels) in splits.items()
    }
    trainer = GGIPNNTrainer(config, vocab)
    params, opt_state = trainer.init_state(pretrained_emb_path=emb_path)
    trainer._state = (params, opt_state)
    run = None
    tl = None
    if run_dir is not None:
        from gene2vec_tpu.models.ggipnn_obs import GGIPNNRun
        from gene2vec_tpu.obs.timeline import PhaseTimeline

        run = GGIPNNRun(run_dir, config=config)
        tl = PhaseTimeline()
        log(f"Writing to {run.out_dir}")
    def drained() -> bool:
        return preempt is not None and preempt.triggered

    import time as _time

    wall_t0 = _time.perf_counter()
    try:
        if run is not None:
            with run.obs.span("fit", train_examples=len(enc["train"][0])):
                params, _ = trainer.fit(
                    *enc["train"], *enc["valid"], log=log, run=run,
                    preempt=preempt, timeline=tl,
                )
            if drained():
                # the grace window is for draining, not for a full
                # test-set pass over a half-trained model
                result = {"interrupted": True}
            else:
                with run.obs.span("test_eval"):
                    result = trainer.evaluate(params, *enc["test"])
                run.obs.event("test_result", **result)
                run.obs.probe()
        else:
            params, _ = trainer.fit(
                *enc["train"], *enc["valid"], log=log, preempt=preempt
            )
            result = (
                {"interrupted": True}
                if drained()
                else trainer.evaluate(params, *enc["test"])
            )
    finally:
        if run is not None:
            if preempt is not None and preempt.triggered:
                run.obs.mark_interrupted("signal", signal=preempt.received)
            # timeline + goodput residue, never masking the in-flight
            # exception (the SGNS trainer's discipline)
            import contextlib
            with contextlib.suppress(Exception):
                from gene2vec_tpu.obs import goodput
                from gene2vec_tpu.obs.timeline import TIMELINE_NAME

                import os as _os

                wall_s = _time.perf_counter() - wall_t0
                preempted_s = 0.0
                if (
                    preempt is not None and preempt.triggered
                    and preempt.received_wall is not None
                ):
                    preempted_s = min(
                        max(_time.time() - preempt.received_wall, 0.0),
                        wall_s,
                    )
                tl.flush(_os.path.join(run.out_dir, TIMELINE_NAME))
                goodput.stamp(run.obs, goodput.summarize(
                    tl.records(), wall_s,
                    pairs_total=trainer._step * config.batch_size,
                    preempted_s=preempted_s,
                ))
            run.close()
    if "accuracy" in result:
        log(f"test accuracy: {result['accuracy']:.4f}")
    if "auc" in result:
        log(f"The AUC score is {result['auc']:.6f}")
    return result
