"""Model families: GGIPNN (gene-gene-interaction MLP) and friends."""

from gene2vec_tpu.models.ggipnn import GGIPNN  # noqa: F401
from gene2vec_tpu.models.ggipnn_data import (  # noqa: F401
    PairTextVocab,
    batch_iter,
    one_hot_labels,
)
from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer  # noqa: F401
