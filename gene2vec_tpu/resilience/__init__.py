"""Crash safety: atomic snapshots, async checkpointing, preemption, chaos.

The failure model and runbook live in docs/RESILIENCE.md.  Four modules:

* :mod:`.snapshot` — write-to-temp + fsync + atomic-rename primitives and
  CRC-stamped per-checkpoint ``MANIFEST`` files, so a torn or bit-rotted
  export is *detectable* (verification), not just unlikely (rename
  atomicity).  ``io/checkpoint.py`` routes every save and every
  discovery scan through this module.
* :mod:`.async_writer` — double-buffered background checkpoint writer:
  the train loop stages a device→host copy and returns; disk I/O runs on
  the writer thread (``ckpt_*`` obs metrics quantify the overhead).
* :mod:`.preempt` — SIGTERM/SIGINT → cooperative drain-checkpoint-exit
  with a distinct exit code (:data:`~gene2vec_tpu.resilience.preempt.
  EXIT_PREEMPTED`) and an ``interrupted=true`` run-manifest stamp.
* :mod:`.chaos` — fault injection (kill a child CLI at step N, truncate
  a checkpoint, corrupt a CRC, delete the newest export) backing
  ``scripts/chaos_drill.py`` and the resilience test suite.
"""

from gene2vec_tpu.resilience.preempt import (  # noqa: F401
    EXIT_PREEMPTED,
    PreemptionHandler,
)
from gene2vec_tpu.resilience.snapshot import (  # noqa: F401
    MANIFEST_SUFFIX,
    VerifyResult,
    verify_manifest,
    write_manifest,
)
