"""Atomic snapshot writes + CRC-stamped manifests.

Failure model (docs/RESILIENCE.md): a writer can die at ANY instruction
— SIGKILL mid-``write``, preemption between two files of one logical
checkpoint, a disk that bit-rots a block after the fact — and a
concurrent reader (the serve registry's watcher thread, a resuming
trainer) must never act on a torn snapshot as if it were complete.  Two
mechanisms, layered:

1. **Atomic visibility** — every file is written to a temp name in the
   same directory, fsync'd, then ``os.replace``'d into place (and the
   directory entry fsync'd).  A reader sees the old file or the new
   file, never a prefix of the new one.
2. **Integrity stamping** — a checkpoint is several files (npz + text
   exports + vocab).  After all of them are in place, a
   ``<prefix>.MANIFEST.json`` listing each file's byte size and CRC32 is
   written (atomically, last).  Discovery treats the manifest as the
   commit record: no manifest → the checkpoint is still being written
   (or died mid-write) and is skipped; CRC/size mismatch → the bytes
   rotted or were truncated after commit, also skipped.

Verification CRCs every covered file, so :func:`verify_manifest` caches
results keyed by the stat signature (mtime_ns, size) of the manifest and
every file it covers — the serve watcher re-polling every few seconds
pays the CRC cost once per actual change, not once per poll.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Dict, Iterable, Optional

SCHEMA = "gene2vec-tpu/snapshot-manifest/v1"
MANIFEST_SUFFIX = ".MANIFEST.json"

_CHUNK_BYTES = 1 << 20


def crc32_file(path: str) -> int:
    """Streaming CRC32 of a file (unsigned)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK_BYTES)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def fsync_dir(dirpath: str) -> None:
    """fsync a directory entry so a completed rename survives power loss
    (best-effort: some filesystems refuse O_RDONLY dir fsync)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _tmp_name(path: str, suffix: str = "") -> str:
    # same directory as the target so os.replace stays a rename, never a
    # cross-device copy; pid-stamped so concurrent writers don't collide
    return f"{path}.tmp{os.getpid()}{suffix}"


def atomic_replace(tmp_path: str, path: str) -> None:
    """fsync ``tmp_path``, rename it onto ``path``, fsync the directory."""
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp_path, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def atomic_write_bytes(path: str, data: bytes) -> None:
    tmp = _tmp_name(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        atomic_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_json(path: str, doc: Dict) -> None:
    atomic_write_bytes(
        path, (json.dumps(doc, indent=1, default=str) + "\n").encode("utf-8")
    )


def atomic_savez(path: str, **arrays) -> None:
    """``np.savez`` with atomic visibility.  ``path`` must end in
    ``.npz`` (savez appends the extension otherwise, which would break
    the temp→final rename pairing)."""
    import numpy as np

    if not path.endswith(".npz"):
        raise ValueError(f"atomic_savez target must end in .npz: {path!r}")
    # temp name keeps the .npz suffix so savez does not append a second one
    tmp = _tmp_name(path[: -len(".npz")]) + ".npz"
    try:
        np.savez(tmp, **arrays)
        atomic_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_copy(src: str, path: str, chunk: int = 1 << 20) -> None:
    """Streaming file copy with atomic visibility — constant memory,
    so copying a production-size table never materializes the whole
    artifact as one bytes object."""
    def _copy(tmp: str) -> None:
        with open(src, "rb") as fin, open(tmp, "wb") as fout:
            while True:
                buf = fin.read(chunk)
                if not buf:
                    break
                fout.write(buf)
    atomic_write_via(_copy, path)


def atomic_write_via(write_fn, path: str) -> None:
    """Run a ``write_fn(path)``-style writer (e.g. the io/emb_io text
    exporters, ``Vocab.save``) against a temp path, then atomically
    rename the result into place."""
    tmp = _tmp_name(path)
    try:
        write_fn(tmp)
        atomic_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# -- manifests ---------------------------------------------------------------


def manifest_path(prefix: str) -> str:
    """``<prefix>.MANIFEST.json`` — the commit record for one logical
    snapshot whose files all start with ``prefix`` or live beside it."""
    return prefix + MANIFEST_SUFFIX


def write_manifest(
    prefix: str, files: Iterable[str], meta: Optional[Dict] = None,
    optional: Iterable[str] = (),
) -> str:
    """Stamp a manifest over ``files`` (paths resolved exactly like any
    other open(); recorded under their basenames, so the whole snapshot
    directory can be moved — every file must live beside ``prefix``).
    Written last, atomically — its existence IS the snapshot's commit.

    Files also listed in ``optional`` are convenience artifacts (the
    per-iteration text exports): verification still catches their
    corruption while they exist, but DELETING one does not invalidate
    the snapshot — an operator reclaiming space from the ~100x-larger
    text twins must not silently un-commit every npz checkpoint."""
    opt_names = {os.path.basename(f) for f in optional}
    entries: Dict[str, Dict] = {}
    for f in files:
        path = os.path.abspath(f)
        name = os.path.basename(path)
        entries[name] = {
            "bytes": os.path.getsize(path),
            "crc32": crc32_file(path),
        }
        if name in opt_names:
            entries[name]["optional"] = True
    doc = {
        "schema": SCHEMA,
        "created_unix": time.time(),
        **(meta or {}),
        "files": entries,
    }
    mpath = manifest_path(prefix)
    atomic_write_json(mpath, doc)
    return mpath


@dataclasses.dataclass(frozen=True)
class VerifyResult:
    ok: bool
    reason: str
    path: str
    manifest: Optional[Dict] = None

    def __bool__(self) -> bool:
        return self.ok


_cache_lock = threading.Lock()
_verify_cache: Dict[str, tuple] = {}
_CACHE_MAX = 256


def stat_sig(path: str):
    """(mtime_ns, size) change signature, or None for a missing path —
    the shared "did these bytes change?" key for the verify cache and
    the registry's quarantine invalidation."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def clear_verify_cache() -> None:
    with _cache_lock:
        _verify_cache.clear()


def verify_manifest(prefix: str, use_cache: bool = True) -> VerifyResult:
    """Check one snapshot's manifest against its bytes on disk.

    ``prefix`` is the checkpoint prefix (or the manifest path itself).
    Returns a falsy :class:`VerifyResult` with a machine-parseable
    ``reason`` (``missing-manifest`` / ``torn-manifest`` /
    ``missing:<name>`` / ``size:<name>`` / ``crc:<name>``) — discovery
    *skips* failed snapshots, it never raises on them."""
    mpath = prefix if prefix.endswith(MANIFEST_SUFFIX) else manifest_path(prefix)
    dirpath = os.path.dirname(os.path.abspath(mpath))

    try:
        with open(mpath, "r", encoding="utf-8") as f:
            doc = json.load(f)
        entries = doc["files"]
    except FileNotFoundError:
        return VerifyResult(False, "missing-manifest", mpath)
    except (OSError, ValueError, KeyError, TypeError):
        return VerifyResult(False, "torn-manifest", mpath)
    if not isinstance(entries, dict) or not all(
        isinstance(e, dict) for e in entries.values()
    ):
        # valid JSON, wrong shape (hand-edited / corrupted): still a
        # falsy verdict — discovery never raises on a bad manifest
        return VerifyResult(False, "torn-manifest", mpath, doc)

    # stat signature over manifest + covered files: unchanged files keep
    # their cached verdict, so the watcher's poll loop CRCs each
    # checkpoint once per change, not once per poll
    sig = tuple(
        [stat_sig(mpath)]
        + [stat_sig(os.path.join(dirpath, name)) for name in sorted(entries)]
    )
    if use_cache:
        with _cache_lock:
            hit = _verify_cache.get(mpath)
        if hit is not None and hit[0] == sig:
            return hit[1]

    result = VerifyResult(True, "ok", mpath, doc)
    for name, entry in entries.items():
        fpath = os.path.join(dirpath, name)
        if not os.path.exists(fpath):
            if entry.get("optional"):
                continue  # deleted convenience artifact, not a torn commit
            result = VerifyResult(False, f"missing:{name}", mpath, doc)
            break
        if os.path.getsize(fpath) != entry.get("bytes"):
            result = VerifyResult(False, f"size:{name}", mpath, doc)
            break
        if crc32_file(fpath) != entry.get("crc32"):
            result = VerifyResult(False, f"crc:{name}", mpath, doc)
            break

    if use_cache:
        with _cache_lock:
            if len(_verify_cache) >= _CACHE_MAX:
                _verify_cache.pop(next(iter(_verify_cache)))
            _verify_cache[mpath] = (sig, result)
    return result


def manifest_bytes(doc: Dict) -> int:
    """Total payload bytes a manifest covers (the ``ckpt_bytes_total``
    feed for the async writer's metrics)."""
    return sum(int(e.get("bytes", 0)) for e in doc.get("files", {}).values())
