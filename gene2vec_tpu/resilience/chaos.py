"""Fault injection for checkpoint/serve resilience drills.

Two kinds of tools, both used by ``scripts/chaos_drill.py`` and
``tests/test_resilience.py``:

* **byte-level injectors** that manufacture the failures the snapshot
  manifests exist to catch — truncation, bit rot, a stale CRC, a
  checkpoint deleted out from under a watcher poll;
* **a child-process harness** that runs the *real* CLIs and kills them
  (SIGKILL/SIGTERM) when a log pattern appears, so "die mid-iteration
  N" is exercised against the actual process tree, not a mock.

Injectors operate on final (committed) files deliberately: rename
atomicity already makes in-progress writes invisible, so the interesting
corruption class is damage AFTER commit, which only the CRC manifest
detects.
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from gene2vec_tpu.resilience import snapshot as snap

# -- byte-level injectors ----------------------------------------------------


def truncate_file(path: str, frac: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Truncate ``path`` to ``keep_bytes`` (or ``frac`` of its size);
    returns the new size.  Models a torn write / lost tail block."""
    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else max(1, int(size * frac))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def flip_byte(path: str, offset: Optional[int] = None, seed: int = 0) -> int:
    """XOR one byte of ``path`` (bit rot); returns the offset hit."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if offset is None:
        offset = int(np.random.RandomState(seed).randint(size))
    with open(path, "rb+") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return offset


def _load_manifest(prefix: str) -> dict:
    """Parse a manifest WITHOUT verifying it — injectors only need the
    JSON; CRC-sweeping the (much larger) artifacts to get it would read
    every byte twice per injection."""
    import json

    with open(snap.manifest_path(prefix), "r", encoding="utf-8") as f:
        return json.load(f)


def corrupt_manifest_crc(prefix: str, name: Optional[str] = None) -> str:
    """Rewrite one CRC entry in a checkpoint's manifest to a wrong value
    (valid JSON, stale stamp) — verification must fail ``crc:<name>``."""
    doc = _load_manifest(prefix)
    if name is None:
        name = sorted(doc["files"])[0]
    doc["files"][name]["crc32"] = (doc["files"][name]["crc32"] ^ 0xDEAD) & 0xFFFFFFFF
    snap.atomic_write_json(snap.manifest_path(prefix), doc)
    return name


def restamp_manifest(prefix: str) -> str:
    """Recompute the manifest's sizes/CRCs from the CURRENT bytes on
    disk — used after an injector to manufacture a checkpoint that
    *passes verification but fails to load* (exercises the registry's
    load-failure / quarantine path rather than its discovery filter)."""
    doc = _load_manifest(prefix)
    dirpath = os.path.dirname(os.path.abspath(prefix))
    for fname, entry in doc["files"].items():
        fpath = os.path.join(dirpath, fname)
        # update in place: flags like "optional" must survive the restamp
        entry["bytes"] = os.path.getsize(fpath)
        entry["crc32"] = snap.crc32_file(fpath)
    mpath = snap.manifest_path(prefix)
    snap.atomic_write_json(mpath, doc)
    return mpath


def delete_iteration(export_dir: str, dim: int, iteration: int) -> List[str]:
    """Remove every file of one iteration (npz first, manifest last —
    the order a hostile cleanup would race a watcher with)."""
    from gene2vec_tpu.io.checkpoint import ckpt_prefix

    prefix = ckpt_prefix(export_dir, dim, iteration)
    removed = []
    for suffix in (".npz", ".txt", "_w2v.txt", snap.MANIFEST_SUFFIX):
        path = prefix + suffix
        if os.path.exists(path):
            os.unlink(path)
            removed.append(path)
    return removed


def load_table(export_dir: str, dim: int, iteration: int) -> np.ndarray:
    """The raw f32 ``emb`` table of one saved iteration — the
    bit-exactness comparand for resume-equivalence drills."""
    from gene2vec_tpu.io.checkpoint import ckpt_prefix

    with np.load(ckpt_prefix(export_dir, dim, iteration) + ".npz") as z:
        return np.asarray(z["emb"], dtype=np.float32)


# -- child-process harness ---------------------------------------------------


@dataclasses.dataclass
class ChildResult:
    argv: List[str]
    returncode: Optional[int]
    output: str
    signaled: bool
    matched_line: Optional[str] = None

    @property
    def lines(self) -> List[str]:
        return self.output.splitlines()


def child_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Child env pinned to the CPU backend: drills are determinism
    checks, and the session env may point at a real accelerator.
    ``PYTHONUNBUFFERED`` makes the child's pipe-connected stdout
    line-buffered — without it, ``run_cli_kill_on``'s pattern matching
    only sees block-flushed output, i.e. usually at exit, and the kill
    lands on an already-finished process."""
    out = dict(os.environ)
    out["JAX_PLATFORMS"] = "cpu"
    out["PYTHONUNBUFFERED"] = "1"
    out.update(env or {})
    return out


def run_cli(argv: Sequence[str], timeout: float = 600.0,
            env: Optional[Dict[str, str]] = None) -> ChildResult:
    """Run a CLI to completion, stdout+stderr merged."""
    proc = subprocess.run(
        list(argv), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout, env=child_env(env),
    )
    return ChildResult(list(argv), proc.returncode, proc.stdout, False)


def run_cli_kill_on(
    argv: Sequence[str],
    pattern: str,
    occurrences: int = 1,
    sig: int = signal.SIGKILL,
    grace_s: float = 0.0,
    timeout: float = 600.0,
    env: Optional[Dict[str, str]] = None,
) -> ChildResult:
    """Spawn a CLI and deliver ``sig`` when ``pattern`` (regex, merged
    stdout+stderr, line-matched) has appeared ``occurrences`` times.

    ``grace_s`` sleeps between match and signal — 0 kills at the log
    line (mid-save for patterns emitted before the checkpoint span),
    larger values land the signal later in the iteration.  Returns once
    the child is gone; ``returncode`` is negative (-signum) for an
    uncaught signal, :data:`~gene2vec_tpu.resilience.preempt.
    EXIT_PREEMPTED` for a drained SIGTERM.
    """
    import queue as _queue
    import threading

    rx = re.compile(pattern)
    proc = subprocess.Popen(
        list(argv), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, bufsize=1, env=child_env(env),
    )
    lines: List[str] = []
    matched: Optional[str] = None
    seen = 0
    deadline = time.monotonic() + timeout
    # the pipe is read on a helper thread so the deadline holds even
    # against a child that hangs SILENTLY (a blocking readline on the
    # main thread would never observe the timeout)
    q: "_queue.Queue" = _queue.Queue()

    def pump() -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            q.put(line)
        q.put(None)  # EOF sentinel

    reader = threading.Thread(target=pump, daemon=True)
    reader.start()
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                proc.kill()
                if matched is not None:
                    # the match + signal happened; the child just refused
                    # to die — report THAT, not a bogus no-match
                    raise TimeoutError(
                        f"{argv!r}: matched {pattern!r} and sent signal "
                        f"{sig}, but the child did not exit within "
                        f"{timeout}s; last output:\n{''.join(lines[-15:])}"
                    )
                raise TimeoutError(
                    f"{argv!r}: no match for {pattern!r} within {timeout}s"
                )
            try:
                line = q.get(timeout=min(remaining, 1.0))
            except _queue.Empty:
                continue
            if line is None:
                break  # child closed stdout (exited)
            lines.append(line)
            if matched is None and rx.search(line):
                seen += 1
                if seen >= occurrences:
                    matched = line.rstrip("\n")
                    if grace_s:
                        time.sleep(grace_s)
                    try:
                        proc.send_signal(sig)
                    except ProcessLookupError:
                        pass
                    # keep draining so a SIGTERM child can log its drain
        try:
            # stdout is closed but the process may linger (atexit, final
            # fsync); give it the remaining budget, floor 5s
            rc = proc.wait(timeout=max(deadline - time.monotonic(), 5.0))
        except subprocess.TimeoutExpired:
            proc.kill()
            raise TimeoutError(
                f"{argv!r}: child closed stdout but did not exit within "
                f"the deadline after signal {sig}"
            ) from None
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    if matched is None:
        raise AssertionError(
            f"{argv!r} exited (rc={proc.returncode}) before matching "
            f"{pattern!r}; output:\n{''.join(lines[-30:])}"
        )
    return ChildResult(list(argv), rc, "".join(lines), True, matched)


def gene2vec_argv(data_dir: str, export_dir: str, **flags) -> List[str]:
    """argv for the real training CLI (the drill's workload), with
    ``--flag value`` kwargs (underscores → dashes; True → bare flag)."""
    argv = [sys.executable, "-m", "gene2vec_tpu.cli.gene2vec",
            data_dir, export_dir, "txt"]
    for k, v in flags.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        elif v is not False and v is not None:
            argv += [flag, str(v)]
    return argv
