"""Double-buffered background checkpoint writer.

The train loop's per-iteration checkpoint is host work (device→host
copy, npz serialization, two text exports, fsync) that the reference
pays inline — on a TPU the device sits idle for the whole write.  This
writer splits the work at the only point that MUST stay synchronous:

* **submit (train loop)** — the caller stages a host snapshot of the
  state (its device→host copy happens *before* ``submit``, because the
  next epoch donates the device buffers) and hands a ``write_fn``
  closure over; ``submit`` itself does no disk I/O (the
  ``ckpt-blocking-io`` graftcheck pass gates this, docs/RESILIENCE.md)
  and returns immediately unless the double-buffer bound is hit;
* **write (background thread)** — ``write_fn`` runs the atomic
  save-with-manifest; durations land in the ``ckpt_write_seconds``
  histogram, payload bytes in ``ckpt_bytes_total``, and queue+in-flight
  occupancy in the ``ckpt_inflight`` gauge.

Double buffering: at most ``max_pending`` writes (default 1) may be
outstanding — staged or in flight — so with the caller's one
being-staged copy the peak is **two** table copies on the host, and a
slow disk back-pressures the train loop (``submit`` blocks until the
previous write retires) instead of accumulating snapshots.

A failed write is never silent: the first error is re-raised (wrapped in
:class:`CheckpointWriteError`) from the next ``submit``/``flush``/
``close`` on the train loop thread — a trainer that cannot persist
progress must crash loudly, not train on with a stale resume point.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from gene2vec_tpu.obs.trace import ambient_span

_STOP = object()


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed (original error chained)."""


class AsyncCheckpointWriter:
    """Background writer with a bounded staging queue.

    ``metrics`` is an obs ``MetricsRegistry`` (optional).  ``write_fn``
    closures may return an ``int`` byte count, which feeds
    ``ckpt_bytes_total``.
    """

    def __init__(self, metrics=None, max_pending: int = 1,
                 name: str = "ckpt-writer"):
        self.metrics = metrics
        self._queue: "queue.Queue" = queue.Queue()
        # the outstanding-writes bound: released by the worker only when
        # a write RETIRES, so queue-slot turnover cannot quietly admit a
        # third live snapshot (staged + queued + writing)
        self._slots = threading.Semaphore(max(1, max_pending))
        self._outstanding = 0
        self._count_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- train-loop side ---------------------------------------------------

    def submit(self, write_fn: Callable[[], Optional[int]], **attrs) -> None:
        """Enqueue one staged snapshot write.  Blocks only while
        ``max_pending`` earlier writes are still outstanding (the
        double-buffer bound); raises the first pending background error
        instead of dropping work after a failure."""
        if self._closed:
            raise CheckpointWriteError("writer is closed")
        self._raise_pending()
        self._slots.acquire()
        with self._count_lock:
            self._outstanding += 1
        self._queue.put((write_fn, attrs))
        self._set_inflight()

    def flush(self) -> None:
        """Block until every submitted write has completed; re-raise the
        first background error."""
        self._queue.join()
        self._raise_pending()

    def close(self) -> None:
        """Flush, stop the thread, and surface any pending error."""
        if self._closed:
            return
        self._closed = True
        self._queue.join()
        self._queue.put(_STOP)
        self._thread.join(timeout=30.0)
        self._raise_pending()

    @property
    def pending(self) -> int:
        """Staged + in-flight writes (the ``ckpt_inflight`` value)."""
        with self._count_lock:
            return self._outstanding

    # -- writer thread -----------------------------------------------------

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                self._queue.task_done()
                return
            write_fn, attrs = item
            t0 = time.perf_counter()
            try:
                with ambient_span("ckpt_write", **attrs):
                    nbytes = write_fn()
                dt = time.perf_counter() - t0
                if self.metrics is not None:
                    self.metrics.histogram("ckpt_write_seconds").observe(dt)
                    self.metrics.counter("ckpt_writes_total").inc()
                    if isinstance(nbytes, int):
                        self.metrics.counter("ckpt_bytes_total").inc(nbytes)
            except BaseException as e:  # surfaced on the train-loop thread
                with self._error_lock:
                    if self._error is None:
                        self._error = e
                if self.metrics is not None:
                    self.metrics.counter("ckpt_errors_total").inc()
            finally:
                with self._count_lock:
                    self._outstanding -= 1
                self._slots.release()
                self._queue.task_done()
                self._set_inflight()

    # -- shared ------------------------------------------------------------

    def _set_inflight(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("ckpt_inflight").set(self.pending)

    def _raise_pending(self) -> None:
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointWriteError(
                f"background checkpoint write failed: {err!r}"
            ) from err

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
