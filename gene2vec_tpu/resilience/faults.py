"""Deterministic HTTP-layer fault injection for the serving stack.

Production fleets treat replica failure, overload, and byzantine
responses as routine; this module manufactures those events *inside the
real server* so the fleet supervisor (``serve/fleet.py``), the resilient
client (``serve/client.py``), and the chaos drill rehearse against the
actual HTTP path, not a mock.  Fault classes (docs/RESILIENCE.md
failure-model table):

* **latency**   — sleep before dispatch (a GC pause, a slow disk);
* **error**     — substitute the response with an HTTP error (default
  503; a replica mid-crash or mid-reload);
* **reset**     — close the TCP connection abruptly with an RST (a
  process SIGKILLed between accept and reply);
* **blackhole** — accept the request and never answer, holding the
  socket open up to ``blackhole_hold_s`` (a wedged handler thread; the
  caller's read timeout is the only way out).

Injection is **deterministic and seedable**: every decision consumes
draws from one seeded RNG in request-arrival order, so a drill replaying
the same request sequence sees the same fault sequence.  The injector is
wired into ``serve/server.py`` behind an explicit flag — the
``--faults`` CLI flag or the ``GENE2VEC_TPU_FAULTS`` env var — and is
completely absent (no RNG draw, no lock) when unconfigured.

The **slow-loris client** (:func:`slow_loris`) is the inverse tool: a
deliberately stalling *client* that sends a request at a trickle, used
by the drill and tests to prove the server's read deadline (408 close)
actually unpins handler threads.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import socket
import threading
import time
from typing import Dict, Optional, Tuple

#: env var ``cli.serve`` consults when ``--faults`` is not given
FAULTS_ENV = "GENE2VEC_TPU_FAULTS"

@dataclasses.dataclass(frozen=True)
class Decision:
    """One injected fault: an optional pre-dispatch delay plus at most
    one terminal action (``error`` with an HTTP status, ``reset``, or
    ``blackhole`` with a hold time).  ``kind is None`` with a positive
    ``delay_s`` is pure added latency — the request then proceeds
    normally."""

    delay_s: float = 0.0
    kind: Optional[str] = None  # "error" | "reset" | "blackhole"
    arg: float = 0.0            # status for error; hold_s for blackhole


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injection policy.  Probabilities are per matching request and
    evaluated in a fixed order (latency first, then exactly one of
    error/reset/blackhole), so a given seed yields one reproducible
    fault schedule."""

    seed: int = 0
    route_prefix: str = "/v1/"  # /healthz etc. stay clean by default
    latency_p: float = 0.0
    latency_ms: float = 0.0
    error_p: float = 0.0
    error_status: int = 503
    reset_p: float = 0.0
    blackhole_p: float = 0.0
    blackhole_hold_s: float = 5.0

    @classmethod
    def from_json(cls, blob: str) -> "FaultSpec":
        doc = json.loads(blob)
        if not isinstance(doc, dict):
            raise ValueError("fault spec must be a JSON object")
        unknown = set(doc) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown fault spec field(s) {sorted(unknown)}")
        return cls(**doc)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class FaultInjector:
    """Draws one fault decision per matching request from a seeded RNG.

    Thread-safe: handler threads serialize on one lock around the RNG so
    the draw sequence is request-arrival-ordered regardless of the
    thread interleaving that delivered them.
    """

    def __init__(self, spec: FaultSpec, metrics=None):
        self.spec = spec
        self.metrics = metrics
        self._rng = random.Random(spec.seed)
        self._lock = threading.Lock()
        self.decisions: Dict[str, int] = {
            "clean": 0, "latency": 0, "error": 0, "reset": 0, "blackhole": 0,
        }

    @classmethod
    def from_env(cls, metrics=None,
                 env_var: str = FAULTS_ENV) -> Optional["FaultInjector"]:
        blob = os.environ.get(env_var)
        if not blob:
            return None
        return cls(FaultSpec.from_json(blob), metrics=metrics)

    def _count(self, kind: str) -> None:
        self.decisions[kind] += 1
        if self.metrics is not None and kind != "clean":
            self.metrics.counter("serve_faults_injected_total").inc()
            self.metrics.counter(f"serve_fault_{kind}_total").inc()

    def decide(self, route: str) -> Optional[Decision]:
        """The fault (if any) for one request on ``route``.  Exactly two
        RNG draws per matching request — one latency draw, one terminal
        draw — regardless of outcome, so the schedule depends only on
        the seed and the request order, never on which faults fired."""
        if not route.startswith(self.spec.route_prefix):
            return None
        with self._lock:
            delay = (
                self.spec.latency_ms / 1000.0
                if self._rng.random() < self.spec.latency_p else 0.0
            )
            u = self._rng.random()
            if u < self.spec.error_p:
                kind: Optional[str] = "error"
                arg: float = float(self.spec.error_status)
            elif u < self.spec.error_p + self.spec.reset_p:
                kind, arg = "reset", 0.0
            elif (u < self.spec.error_p + self.spec.reset_p
                  + self.spec.blackhole_p):
                kind, arg = "blackhole", float(self.spec.blackhole_hold_s)
            else:
                kind, arg = None, 0.0
            if kind is not None:
                self._count(kind)
            if delay:
                self._count("latency")
            if kind is None and not delay:
                self._count("clean")
        if kind is None and not delay:
            return None
        return Decision(delay_s=delay, kind=kind, arg=arg)


def apply_reset(sock: socket.socket) -> None:
    """Close ``sock`` with an RST instead of a FIN: SO_LINGER with a zero
    timeout makes close() abort the connection, which the peer observes
    as ``ConnectionResetError`` — the signature of a replica that died
    mid-exchange rather than one that answered and hung up."""
    import struct

    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass  # already dead; the close below is best-effort either way
    try:
        sock.close()
    except OSError:
        pass


def slow_loris(
    host: str,
    port: int,
    path: str = "/v1/similar",
    total_body: int = 4096,
    drip_bytes: int = 1,
    drip_interval_s: float = 0.5,
    duration_s: float = 10.0,
    connect_timeout_s: float = 5.0,
) -> Tuple[Optional[int], float]:
    """A deliberately stalling client: send headers promising
    ``total_body`` bytes, then drip the body ``drip_bytes`` at a time
    every ``drip_interval_s`` for up to ``duration_s``.

    Returns ``(status, held_s)`` — the HTTP status the server eventually
    answered with (``408`` when its read deadline fired; ``None`` when
    the server never answered and the loris gave up) and how long the
    connection was held.  A server WITHOUT a read deadline holds a
    handler thread for the whole ``duration_s``; one with the deadline
    answers 408 and closes in ~its timeout.
    """
    t0 = time.monotonic()
    sock = socket.create_connection((host, port), timeout=connect_timeout_s)
    try:
        head = (
            f"POST {path} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {total_body}\r\n"
            "\r\n"
        )
        sock.sendall(head.encode("ascii"))
        sent = 0
        deadline = t0 + duration_s
        sock.settimeout(max(drip_interval_s, 0.05))
        status: Optional[int] = None
        while sent < total_body and time.monotonic() < deadline:
            try:
                sock.sendall(b"x" * min(drip_bytes, total_body - sent))
                sent += drip_bytes
            except OSError:
                break  # server closed on us — go read the status, if any
            # between drips, poll for an early server verdict (the 408)
            try:
                raw = sock.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            if raw:
                try:
                    status = int(raw.split(b" ", 2)[1])
                except (IndexError, ValueError):
                    status = -1
            break
        if status is None:
            # one last listen: the server may answer at close
            try:
                sock.settimeout(1.0)
                raw = sock.recv(4096)
                if raw:
                    status = int(raw.split(b" ", 2)[1])
            except (OSError, IndexError, ValueError):
                pass
        return status, time.monotonic() - t0
    finally:
        try:
            sock.close()
        except OSError:
            pass
