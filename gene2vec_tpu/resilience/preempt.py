"""Cooperative preemption: SIGTERM/SIGINT → drain, checkpoint, exit.

TPU pools and batch schedulers preempt with SIGTERM and a grace window.
The default Python disposition (KeyboardInterrupt for SIGINT, hard death
for SIGTERM) can land anywhere — including between a checkpoint's npz
rename and its manifest commit — and loses the RNG/data-cursor position
of the running iteration.  :class:`PreemptionHandler` converts the
signal into a flag the training loops poll at iteration boundaries:
finish the current epoch, finish its checkpoint (a *committed* resume
point, manifest and all), stamp the obs run manifest
``interrupted=true``, and exit with :data:`EXIT_PREEMPTED` so harnesses
can distinguish "preempted, resume me" from success (0), failure (1/2),
and a watchdog timeout (124).

A second signal while draining restores the previous disposition and
re-raises — an operator double-Ctrl-C still kills promptly.
"""

from __future__ import annotations

import signal
import threading
from typing import Dict, Optional, Tuple

#: exit status for a clean preemption drain ("resume me"), distinct from
#: success (0), error (1), internal failure (2), and timeout(1)'s 124.
EXIT_PREEMPTED = 113


class PreemptionHandler:
    """Installable SIGTERM/SIGINT → flag converter.

    ``install()`` must run on the main thread (CPython restricts
    ``signal.signal``); loops on any thread may poll
    :attr:`triggered`.  Tests and non-main-thread embedders call
    :meth:`trigger` directly.
    """

    def __init__(
        self, signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)
    ):
        self.signals = signals
        self.received: Optional[int] = None
        #: wall clock (time.time()) when the first signal landed — the
        #: goodput "preempted" bucket measures the drain tail from here
        self.received_wall: Optional[float] = None
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "PreemptionHandler":
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError, OSError):
                pass
        self._prev.clear()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- signal path -------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        if self._event.is_set():
            # second signal: the drain is taking too long for the sender
            # — restore previous dispositions and re-deliver for a
            # prompt (default) death
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.trigger(signum)

    def trigger(self, signum: Optional[int] = None) -> None:
        """Mark preemption requested (the signal handler's body; also
        the test/embedder entry point)."""
        if self.received is None:
            self.received = signum
            import time

            self.received_wall = time.time()
        self._event.set()

    # -- polling -----------------------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)
