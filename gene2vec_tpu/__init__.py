"""gene2vec_tpu — a TPU-native gene-embedding framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the reference
Gene2vec pipeline (corpus construction → skip-gram embedding training →
intrinsic/extrinsic evaluation → visualization), built TPU-first:

  * the SGNS/CBOW/HS hot loop is a single jitted ``lax.scan`` over the whole
    epoch with the corpus, negative-sampling table and both embedding tables
    resident in HBM (reference: gensim Cython Hogwild threads,
    ``src/gene2vec.py:70,87``);
  * scale-out is expressed as ``jax.sharding`` specs over a Mesh — data
    parallelism shards the pair stream, model parallelism shards the
    embedding-table rows over the vocab axis — with XLA inserting the
    collectives (reference has no distributed backend at all, SURVEY §2.4);
  * the GGIPNN gene-gene-interaction MLP is Flax + optax on the same
    on-device table (reference: TF1 graph with the table pinned to
    ``/cpu:0``, ``src/GGIPNN.py:18``);
  * native C++ components live in ``native/``: an mmap'ed pair-corpus
    reader/encoder and a Hogwild SGNS CPU oracle that stands in for the
    gensim baseline.
"""

__version__ = "0.1.0"

from gene2vec_tpu.config import SGNSConfig, GGIPNNConfig, MeshConfig  # noqa: F401
