"""Pair-corpus builder: per-study co-expression thresholding.

Behavioral re-design of ``src/generate_gene_pairs.py``.  The recipe
(SURVEY §2.2 #15), preserved exactly:

* keep studies with ≥ ``min_study_samples`` samples (``:163-164``);
* per study: drop genes whose **per-study** total raw counts are < 10
  (``:88-95``), replace zeros with half of the **global** non-zero minimum
  of the full TPM matrix (``:73-79,99`` — global, not per-study, a quirk we
  keep), log2 (``:100-101``);
* optionally map ``ENSEMBL|SYMBOL`` gene ids to symbols, dropping empty and
  non-unique symbols (``:105-125``);
* abs Pearson correlation > threshold emits a pair; the scan over the full
  symmetric matrix emits **both (i, j) and (j, i)**, diagonal excluded
  (``:59-63``), so every co-expressed pair appears twice in the corpus.

TPU-first hot loop: the reference's ``data.corr()`` (``:49``) is
O(genes² · samples) BLAS per study.  Here correlation is computed as one
standardized matmul — corr = ZᵀZ/(n−1) with Z the column-standardized
matrix — which ``backend="jax"`` runs on the TPU MXU in float32 (genes² ≫
samples, a textbook systolic-array workload).  Zero-variance columns are
masked out (pandas yields NaN there, which never passes the threshold).

Parallelism: the reference ships a Ray cluster for what is an
embarrassingly parallel per-study map (``:167-191``); here ``parallel=True``
uses a ``multiprocessing.Pool`` — no cluster runtime — and the JAX backend
typically makes even the serial path faster than parallel CPU pandas.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

MIN_TOTAL_COUNTS = 10.0


def half_min(x: np.ndarray) -> float:
    """Half of the smallest positive entry (zero-replacement value)."""
    pos = x[x > 0]
    if pos.size == 0:
        raise ValueError("matrix has no positive entries")
    return float(pos.min() / 2.0)


def _split_gene_ids(gene_ids: Sequence[str]) -> Tuple[List[str], List[str]]:
    """'ENSEMBL|SYMBOL' ids → (ensembl list, symbol list; '' if absent)."""
    ens, sym = [], []
    for gid in gene_ids:
        parts = str(gid).split("|")
        ens.append(parts[0])
        sym.append(parts[1] if len(parts) > 1 else "")
    return ens, sym


def clean_and_normalize(
    data,
    gene_counts,
    sample_ids: Optional[List[str]] = None,
    *,
    min_total_counts: float = MIN_TOTAL_COUNTS,
    global_half_min: Optional[float] = None,
):
    """Per-study cleaned + log2 TPM slice (pandas in, pandas out).

    ``data``: samples × genes TPM; ``gene_counts``: raw counts with a
    ``gene_id`` column and per-sample columns.  Gene totals are computed
    over the study's samples; the zero-replacement half-min over the
    **global** matrix.
    """
    import pandas as pd

    if sample_ids is None:
        sample_ids = data.index.tolist()
    ens, _ = _split_gene_ids(gene_counts["gene_id"])
    totals = pd.Series(
        index=ens, data=gene_counts.loc[:, sample_ids].sum(axis=1).values
    )
    keep = totals >= min_total_counts
    sub = data.loc[sample_ids, keep.values].copy()
    hm = half_min(data.values) if global_half_min is None else global_half_min
    sub = sub.replace(0.0, hm)
    return np.log2(sub)


def gene_annotated_data(
    data,
    gene_counts,
    sample_ids: Optional[List[str]] = None,
    *,
    min_total_counts: float = MIN_TOTAL_COUNTS,
    global_half_min: Optional[float] = None,
):
    """clean_and_normalize + rename columns to gene symbols, keeping only
    genes with a non-empty, unique symbol."""
    normed = clean_and_normalize(
        data,
        gene_counts,
        sample_ids,
        min_total_counts=min_total_counts,
        global_half_min=global_half_min,
    )
    ens, sym = _split_gene_ids(gene_counts["gene_id"])
    names = dict(zip(ens, sym))
    normed = normed.rename(columns=names)
    normed = normed.loc[:, normed.columns != ""]
    vc = normed.columns.value_counts()
    return normed.loc[:, vc.index[vc == 1]]


def _standardized_columns(matrix: np.ndarray):
    """(z, n): columns centered and scaled to unit sample-variance; zero-
    variance columns become all-zero (they can never pass a positive
    threshold — matching pandas' NaN-never-compares behavior)."""
    x = np.asarray(matrix, dtype=np.float64)
    n = x.shape[0]
    mean = x.mean(axis=0)
    std = x.std(axis=0, ddof=1)
    ok = std > 0
    return np.where(ok, (x - mean) / np.where(ok, std, 1.0), 0.0), n


def abs_correlation(matrix: np.ndarray, backend: str = "numpy") -> np.ndarray:
    """|Pearson correlation| between columns, as a standardized matmul."""
    z, n = _standardized_columns(matrix)
    if backend == "jax":
        import jax
        import jax.numpy as jnp

        zj = jnp.asarray(z, dtype=jnp.float32)
        # HIGHEST keeps full f32 on the MXU — the default bf16 passes loses
        # ~3 decimal digits, enough to flip pairs sitting near the 0.9
        # threshold.
        prod = jnp.matmul(zj.T, zj, precision=jax.lax.Precision.HIGHEST)
        corr = np.asarray(jnp.abs(prod) / (n - 1))
    elif backend == "numpy":
        corr = np.abs(z.T @ z) / (n - 1)
    else:
        raise ValueError(f"unknown backend {backend!r}")
    return np.clip(corr, 0.0, 1.0)


def abs_correlation_mask(
    matrix: np.ndarray, threshold: float, backend: str = "numpy"
) -> np.ndarray:
    """(genes, genes) bool mask of ``|corr| > threshold``.

    The corpus builder only ever consumes the thresholded mask, so the
    jax backend compares ON DEVICE and downloads packed bits — genes²/8
    bytes, 32x less host-link traffic than the f32 matrix.  At GEO-study
    shapes the matmul is trivial for the MXU and the device→host link is
    the whole cost of the TPU path (measured: the full-matrix download
    made backend="jax" *slower* than numpy end to end; see
    docs/PERF_NOTES.md round 4, viz/corpus benchmarks).
    """
    if backend != "jax":
        return abs_correlation(matrix, backend=backend) > threshold
    import jax
    import jax.numpy as jnp

    z, n = _standardized_columns(matrix)
    g = z.shape[1]
    zj = jnp.asarray(z, dtype=jnp.float32)
    prod = jnp.matmul(zj.T, zj, precision=jax.lax.Precision.HIGHEST)
    # same clip as abs_correlation so the backends agree even at
    # threshold >= 1.0 (fp error can push |corr| past 1)
    corr = jnp.clip(jnp.abs(prod) / (n - 1), 0.0, 1.0)
    bits = np.asarray(jnp.packbits((corr > threshold).reshape(-1)))
    return np.unpackbits(bits, count=g * g).astype(bool).reshape(g, g)


def coexpression_pairs(
    normed, *, corr_threshold: float = 0.9, backend: str = "numpy"
) -> List[str]:
    """'g1 g2' lines for every |corr| > threshold column pair — both
    directions, no self-pairs."""
    genes = list(normed.columns)
    mask = abs_correlation_mask(
        normed.values, corr_threshold, backend=backend
    )
    rows, cols = mask.nonzero()
    return [f"{genes[r]} {genes[c]}" for r, c in zip(rows, cols) if r != c]


def _study_pairs(args) -> List[str]:
    (
        data,
        gene_counts,
        sample_ids,
        ensembl,
        corr_threshold,
        min_total_counts,
        ghm,
        backend,
    ) = args
    fn = clean_and_normalize if ensembl else gene_annotated_data
    normed = fn(
        data,
        gene_counts,
        sample_ids,
        min_total_counts=min_total_counts,
        global_half_min=ghm,
    )
    return coexpression_pairs(
        normed, corr_threshold=corr_threshold, backend=backend
    )


def build_pairs(
    query_dir: str,
    out_path: Optional[str] = None,
    *,
    corr_threshold: float = 0.9,
    min_study_samples: int = 20,
    min_total_counts: float = MIN_TOTAL_COUNTS,
    ensembl: bool = False,
    parallel: bool = False,
    num_workers: Optional[int] = None,
    backend: str = "numpy",
    log: Callable[[str], None] = print,
    run_dir: Optional[str] = None,
) -> List[str]:
    """End-to-end: query dir (``data/SRARunTable.csv``,
    ``data/gene_counts_TPM.csv``, ``data/gene_counts.csv``) → pair lines,
    optionally written to ``out_path``.

    ``run_dir`` observes the build (docs/OBSERVABILITY.md): manifest +
    per-study spans/pair counts, so slow or pair-heavy studies are
    attributable from ``obs report``.
    """
    run = None
    if run_dir is not None:
        from gene2vec_tpu.obs.run import Run

        run = Run(
            run_dir, name="generate_pairs",
            config={
                "query_dir": query_dir, "corr_threshold": corr_threshold,
                "min_study_samples": min_study_samples,
                "min_total_counts": min_total_counts, "ensembl": ensembl,
                "parallel": parallel, "backend": backend,
            },
            # never initialize a jax backend here, even for backend=jax:
            # the parallel path forks an mp.Pool below, and a client
            # initialized before the fork hangs/crashes the workers.
            # Backend facts are annotated after the correlation work.
            probe_devices=False,
        )
    try:
        return _build_pairs_observed(
            query_dir, out_path, corr_threshold, min_study_samples,
            min_total_counts, ensembl, parallel, num_workers, backend, log,
            run,
        )
    finally:
        if run is not None:
            run.close()


def _build_pairs_observed(
    query_dir, out_path, corr_threshold, min_study_samples,
    min_total_counts, ensembl, parallel, num_workers, backend, log, run,
) -> List[str]:
    import contextlib

    import pandas as pd

    span = run.span if run is not None else (
        lambda name, **a: contextlib.nullcontext({})
    )

    with span("load_inputs"):
        run_table = pd.read_csv(
            os.path.join(query_dir, "data", "SRARunTable.csv"), index_col=0
        )
        data = pd.read_csv(
            os.path.join(query_dir, "data", "gene_counts_TPM.csv"), index_col=0
        )
        gene_counts = pd.read_csv(
            os.path.join(query_dir, "data", "gene_counts.csv")
        )
        data = data.loc[run_table.index.tolist()]

    study_counts = run_table["SRA Study"].value_counts()
    studies = study_counts.index[study_counts >= min_study_samples].tolist()
    log(f"{len(studies)} studies with ≥{min_study_samples} samples")

    ghm = half_min(data.values)  # global, computed once (reference quirk)
    jobs = [
        (
            data,
            gene_counts,
            run_table.index[run_table["SRA Study"] == s].tolist(),
            ensembl,
            corr_threshold,
            min_total_counts,
            ghm,
            backend,
        )
        for s in studies
    ]
    if parallel and len(jobs) > 1:
        import multiprocessing as mp

        # pool workers carry no tracer; the map is one span, per-study
        # pair counts land as events afterwards
        with span("correlate_studies", n_studies=len(jobs), parallel=True):
            with mp.Pool(num_workers or os.cpu_count()) as pool:
                results = pool.map(_study_pairs, jobs)
        if run is not None:
            for s, r in zip(studies, results):
                run.event("study", study=str(s), n_pairs=len(r))
    else:
        results = []
        for s, j in zip(studies, jobs):
            with span("study", study=str(s), n_samples=len(j[2])) as out:
                r = _study_pairs(j)
                out["n_pairs"] = len(r)
            results.append(r)

    pairs = [p for r in results for p in r]
    if run is not None:
        run.registry.counter("studies_total").inc(len(studies))
        run.registry.counter("pairs_total").inc(len(pairs))
        run.annotate_backend()  # jax (if used) is initialized by now
        run.probe()
    log(f"{len(pairs):,} total co-expression gene pairs computed")
    if out_path is not None:
        with span("write_output", path=out_path):
            with open(out_path, "w", encoding="utf-8") as f:
                f.write("\n".join(pairs))
        log(f"wrote {out_path}")
    return pairs
