"""Co-expression pair-corpus construction (layer L1)."""

from gene2vec_tpu.corpus.builder import (  # noqa: F401
    abs_correlation,
    build_pairs,
    clean_and_normalize,
    coexpression_pairs,
    gene_annotated_data,
    half_min,
)
