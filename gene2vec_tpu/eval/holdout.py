"""THE seen-gene holdout protocol — the framework's real-data quality
measurement, shared by ``scripts/run_real_auc.py``, ``bench.py``'s quality
gate, and ``experiments/quality_matrix.py`` so their numbers stay
comparable (one seed, one split, one embedding corpus definition).

Why this protocol exists: the reference's train/valid/test splits are
pairwise gene-disjoint, and its GGIPNN backfills unseen genes with random
rows (``/root/reference/src/GGIPNN_util.py:6-14``), so test-split AUC
measures nothing about an embedding trained on in-repo data — the
published score needs the non-distributed pretrained GEO embedding.  The
measurable task is link prediction over *seen* genes: hold out a fraction
of the train split's pairs, train SGNS on the remaining positives, rank
the held-out pairs.  See docs/QUALITY_NOTES.md §1.

Protocol constants are frozen here; changing them invalidates every
recorded number (REAL_AUC.json, BENCH quality gates, QUALITY_NOTES
tables) at once rather than silently forking them.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np

HOLDOUT_SEED = 7
HOLDOUT_FRACTION = 0.2
#: what the sequential CPU oracle measures under this exact protocol
#: (in-vocab cosine AUC, 50 epochs) — the parity reference for gates.
ORACLE_COS_AUC = 0.878
#: a no-embedding degree-product baseline on the same holdout — context
#: for reading AUC values: this metric has a strong co-occurrence floor,
#: and scores far ABOVE the oracle signal estimator degeneration toward
#: raw co-occurrence statistics, not better embeddings (the gate
#: therefore ANDs loss escape + planted separation with the AUC check;
#: docs/QUALITY_NOTES.md §8).
DEGREE_BASELINE_AUC = 0.859
#: the gate threshold: above the no-embedding degree floor (a gate that
#: accepts less than "no embedding at all" would be vacuous on this
#: axis) while leaving ~0.015 slack under the oracle's 0.878 for
#: config/seed noise; bench.py withholds its headline below this.
#: Converged runs measure 0.886-0.898.
GATE_MIN_AUC = 0.862
#: upper sanity bound (VERDICT r3 item 7): this metric rewards raw
#: co-occurrence statistics, so an AUC far ABOVE the oracle signals
#: estimator degeneration, not a better embedding — the broken P=64
#: shared pool scores 0.9613 while its loss never moves
#: (docs/QUALITY_NOTES.md §8).  Healthy converged runs measure
#: 0.886-0.898; 0.92 leaves seed/config slack above that band while
#: rejecting the degenerate regime.  bench.py withholds the headline
#: above this too.
GATE_MAX_AUC = 0.92


def auc_in_gate_band(auc: float) -> bool:
    """The two-sided gate decision on the holdout cosine AUC: at least
    GATE_MIN_AUC (it must beat the degree floor with oracle slack) and at
    most GATE_MAX_AUC (far above the oracle = co-occurrence degeneration,
    QUALITY_NOTES §8 — the "too good" runs are the broken ones).  NaN
    (diverged embedding) fails both sides."""
    return bool(GATE_MIN_AUC <= auc <= GATE_MAX_AUC)


def read_split(data_dir: str, split: str) -> Tuple[List[List[str]], np.ndarray]:
    """One reference-format split: pair lines + int labels."""
    with open(f"{data_dir}/{split}_text.txt") as f:
        lines = [ln.split() for ln in f if ln.strip()]
    with open(f"{data_dir}/{split}_label.txt") as f:
        labels = [int(ln) for ln in f if ln.strip()]
    if len(lines) != len(labels):
        raise ValueError(
            f"{split}: {len(lines)} pair lines vs {len(labels)} labels"
        )
    return lines, np.asarray(labels)


class HoldoutSplit(NamedTuple):
    fit_pairs: List[List[str]]    # classifier training pairs (all labels)
    fit_labels: np.ndarray
    hold_pairs: List[List[str]]   # evaluation pairs — never trained on
    hold_labels: np.ndarray
    fit_positives: List[List[str]]  # THE embedding corpus (fit positives)


def holdout_split(
    lines: List[List[str]],
    labels: np.ndarray,
    fraction: float = HOLDOUT_FRACTION,
    seed: int = HOLDOUT_SEED,
) -> HoldoutSplit:
    """The canonical pair-level split.  The embedding corpus is ALL fit
    positives — a monitoring dev slice, if a caller wants one, must be
    carved from ``fit_pairs`` *after* this split and must not shrink the
    embedding corpus (that drift made round-3 scripts non-comparable)."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(lines))
    n_hold = int(len(lines) * fraction)
    hold_idx, fit_idx = perm[:n_hold], perm[n_hold:]
    fit_pairs = [lines[i] for i in fit_idx]
    fit_labels = labels[fit_idx]
    return HoldoutSplit(
        fit_pairs=fit_pairs,
        fit_labels=fit_labels,
        hold_pairs=[lines[i] for i in hold_idx],
        hold_labels=labels[hold_idx],
        fit_positives=[p for p, y in zip(fit_pairs, fit_labels) if y == 1],
    )


def load_holdout(data_dir: str):
    """The one canonical construction of (embedding PairCorpus, split):
    read the reference train split, apply :func:`holdout_split`, and build
    the corpus from ALL fit positives.  bench.py's gate, the experiment
    suites, and run_real_auc.py must all go through here — hand-rolled
    copies are exactly the corpus-definition drift this module exists to
    prevent."""
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    lines, labels = read_split(data_dir, "train")
    split = holdout_split(lines, labels)
    vocab = Vocab.from_pairs(split.fit_positives)
    return PairCorpus(vocab, vocab.encode_pairs(split.fit_positives)), split


def cosine_scores(
    token_to_id, matrix: np.ndarray, pairs: List[List[str]]
) -> Tuple[np.ndarray, np.ndarray]:
    """(cosine score per pair, in-vocab mask).  Out-of-vocab pairs score
    0.0 — genes absent from every positive fit pair are themselves a
    negative signal, but gates should use the in-vocab subset, where the
    ranking comes entirely from learned geometry."""
    m = matrix / (np.linalg.norm(matrix, axis=1, keepdims=True) + 1e-9)
    scores = np.zeros(len(pairs))
    in_vocab = np.zeros(len(pairs), bool)
    for i, (a, b) in enumerate(pairs):
        ia, ib = token_to_id.get(a), token_to_id.get(b)
        if ia is not None and ib is not None:
            scores[i] = float(m[ia] @ m[ib])
            in_vocab[i] = True
    return scores, in_vocab


def holdout_cos_auc(
    vocab, emb: np.ndarray, split: HoldoutSplit, in_vocab_only: bool = True
) -> float:
    """In-vocab holdout cosine AUC — the gate metric (oracle: 0.878)."""
    from gene2vec_tpu.eval.metrics import roc_auc_score

    scores, mask = cosine_scores(vocab.token_to_id, emb, split.hold_pairs)
    if in_vocab_only:
        return roc_auc_score(split.hold_labels[mask], scores[mask])
    return roc_auc_score(split.hold_labels, scores)
