"""Evaluation suite: intrinsic target function, extrinsic AUC, parity
harness, and the canonical seen-gene holdout protocol."""

from gene2vec_tpu.eval.holdout import (  # noqa: F401
    DEGREE_BASELINE_AUC,
    GATE_MIN_AUC,
    HOLDOUT_FRACTION,
    HOLDOUT_SEED,
    ORACLE_COS_AUC,
    HoldoutSplit,
    holdout_cos_auc,
    holdout_split,
    load_holdout,
    read_split,
)
from gene2vec_tpu.eval.metrics import roc_auc_score  # noqa: F401
