"""Evaluation suite: intrinsic target function, extrinsic AUC, parity harness."""

from gene2vec_tpu.eval.metrics import roc_auc_score  # noqa: F401
