"""THE planted-cluster collapse check — shared by ``bench.py``'s quality
gate, ``experiments/quality_matrix.py``, and
``tests/test_quality_regression.py`` so all three measure the same thing
(the check exists because designs can pass any intra-only criterion while
inter-cluster cosine drifts to ~1 — docs/QUALITY_NOTES.md §2-§3).

A corpus of ``n_clusters`` disjoint gene cliques trained with the default
config must yield intra-cluster cosine > INTRA_MIN while inter-cluster
cosine stays < INTER_MAX.  Constants are frozen here; changing them
re-calibrates the bench gate, the experiment tables, and the regression
tests at once rather than silently forking them.
"""

from __future__ import annotations

import itertools
from typing import Tuple

import numpy as np

N_CLUSTERS = 10
N_GENES = 20
PAIRS_PER_CLUSTER = 2000
INTRA_MIN = 0.95
INTER_MAX = 0.6


def planted_corpus(
    n_clusters: int = N_CLUSTERS,
    n_genes: int = N_GENES,
    pairs_per: int = PAIRS_PER_CLUSTER,
    seed: int = 0,
):
    """(vocab, PairCorpus) of ``n_clusters`` disjoint gene cliques."""
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(seed)
    lines = []
    for c in range(n_clusters):
        genes = [f"C{c}G{i}" for i in range(n_genes)]
        for _ in range(pairs_per):
            a, b = rng.choice(n_genes, 2, replace=False)
            lines.append((genes[a], genes[b]))
    vocab = Vocab.from_pairs(lines)
    return vocab, PairCorpus(vocab, vocab.encode_pairs(lines))


def cluster_cosines(
    vocab,
    emb: np.ndarray,
    n_clusters: int = N_CLUSTERS,
    n_genes: int = N_GENES,
) -> Tuple[float, float]:
    """(mean intra-cluster cosine, mean inter-cluster cosine)."""
    m = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9)
    idx = vocab.token_to_id
    rng = np.random.RandomState(1)
    intra, inter = [], []
    for c in range(n_clusters):
        rows = [idx[f"C{c}G{i}"] for i in range(8)]
        for a, b in itertools.combinations(rows, 2):
            intra.append(m[a] @ m[b])
    for _ in range(500):
        c1, c2 = rng.choice(n_clusters, 2, replace=False)
        inter.append(
            m[idx[f"C{c1}G{rng.randint(n_genes)}"]]
            @ m[idx[f"C{c2}G{rng.randint(n_genes)}"]]
        )
    return float(np.mean(intra)), float(np.mean(inter))
