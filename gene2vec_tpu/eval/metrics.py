"""Small numpy metrics — no sklearn dependency in the core path.

The reference computes its extrinsic score with ``sklearn.metrics.roc_auc_score``
on the positive-class softmax column (``src/GGIPNN_Classification.py:246-254``).
The ranking form here (Mann-Whitney U with midrank ties) is numerically
identical for binary labels and keeps the core framework dependency-light.
"""

from __future__ import annotations

import numpy as np


def _midranks(x: np.ndarray) -> np.ndarray:
    """Ranks (1-based) with ties assigned the midrank."""
    order = np.argsort(x, kind="mergesort")
    sx = x[order]
    n = len(x)
    ranks = np.empty(n, dtype=np.float64)
    i = 0
    while i < n:
        j = i
        while j + 1 < n and sx[j + 1] == sx[i]:
            j += 1
        ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return ranks


def roc_auc_score(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Binary ROC-AUC via the rank statistic.

    ``y_true`` ∈ {0, 1}; ``y_score`` any real-valued score (the reference
    feeds softmax ``scores[:, 1]``).
    """
    y_true = np.asarray(y_true).ravel()
    y_score = np.asarray(y_score, dtype=np.float64).ravel()
    if y_true.shape != y_score.shape:
        raise ValueError("y_true and y_score shape mismatch")
    pos = y_true == 1
    n_pos = int(pos.sum())
    n_neg = int(len(y_true) - n_pos)
    if n_pos == 0 or n_neg == 0:
        raise ValueError("roc_auc_score needs both classes present")
    ranks = _midranks(y_score)
    u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


def accuracy(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    y_true = np.asarray(y_true).ravel()
    y_pred = np.asarray(y_pred).ravel()
    return float((y_true == y_pred).mean())
