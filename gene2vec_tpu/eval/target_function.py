"""Intrinsic evaluation — the "target function".

score = (mean intra-pathway cosine similarity) / (mean random-pair cosine
similarity), the de-facto correctness oracle for trained embeddings
(``src/evaluation_target_function.py:16-60``).  Semantics preserved:

* MSigDB ``.gmt`` pathways with more than 50 genes are skipped — the
  reference keeps lines with ≤52 tab fields: name, url, ≤50 genes
  (``src/evaluation_target_function.py:5-14``);
* pathways contribute only genes present in the embedding; pathways with
  <2 present genes are skipped (``combinations`` yields nothing);
* the denominator shuffles the embedding's gene list with
  ``random.seed(35)`` and averages all C(1000, 2) pair similarities
  (``src/evaluation_target_function.py:44-50``).

The reference computes this with an O(V) list-scan membership test per gene
and a Python loop over every pair (SURVEY §2.2 #14).  Here each pathway's
mean pairwise cosine collapses to one norm: with unit rows u_i,

    mean_{i<j} u_i·u_j = (‖Σ_i u_i‖² − n) / (n (n − 1)),

so the whole evaluation is one row-normalization plus a segment-sum — no
per-pair work at all.
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Tuple

import numpy as np

from gene2vec_tpu.io.emb_io import load_embedding_any

MAX_PATHWAY_GENES = 50
RANDOM_PAIR_GENES = 1000
RANDOM_SEED = 35


def load_gmt(path: str, max_genes: int = MAX_PATHWAY_GENES) -> Dict[str, List[str]]:
    """Pathway name → gene list from an MSigDB ``.gmt`` file (tab-separated:
    name, url, genes…), keeping pathways with at most ``max_genes`` genes."""
    pathways: Dict[str, List[str]] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            fields = line.rstrip("\n").split("\t")
            if len(fields) < 3 or len(fields) > max_genes + 2:
                continue
            pathways[fields[0]] = [g for g in fields[2:] if g]
    return pathways


def _unit_rows(matrix: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(matrix, axis=1, keepdims=True)
    return matrix / np.maximum(norms, 1e-12)


def mean_pairwise_cosine(unit: np.ndarray) -> float:
    """Mean over all C(n,2) pairwise cosine similarities of unit rows,
    via the sum-of-vectors identity (exact, no pair loop)."""
    n = unit.shape[0]
    if n < 2:
        raise ValueError("need at least 2 rows")
    s = unit.sum(axis=0)
    return float((s @ s - n) / (n * (n - 1)))


def target_function(
    emb_path: str,
    gmt_path: str,
    *,
    max_pathway_genes: int = MAX_PATHWAY_GENES,
    num_random_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    """The reference's ``targetFunc`` on any supported embedding file."""
    tokens, matrix = load_embedding_any(emb_path)
    pathways = load_gmt(gmt_path, max_pathway_genes)
    return target_function_arrays(
        tokens,
        matrix,
        pathways,
        num_random_genes=num_random_genes,
        seed=seed,
    )


def target_function_arrays(
    tokens: Sequence[str],
    matrix: np.ndarray,
    pathways: Dict[str, List[str]],
    *,
    num_random_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    numerator, _ = pathway_similarities(tokens, matrix, pathways)
    denominator = random_pair_similarity(
        tokens, matrix, num_genes=num_random_genes, seed=seed
    )
    return numerator / denominator


def pathway_similarities(
    tokens: Sequence[str],
    matrix: np.ndarray,
    pathways: Dict[str, List[str]],
) -> Tuple[float, Dict[str, float]]:
    """(mean over pathways, per-pathway mean intra-pathway cosine)."""
    token_to_id = {t: i for i, t in enumerate(tokens)}
    unit = _unit_rows(np.asarray(matrix, dtype=np.float64))
    per_pathway: Dict[str, float] = {}
    for name, genes in pathways.items():
        idx = [token_to_id[g] for g in genes if g in token_to_id]
        if len(idx) < 2:
            continue
        per_pathway[name] = mean_pairwise_cosine(unit[idx])
    if not per_pathway:
        raise ValueError("no pathway had ≥2 genes present in the embedding")
    return float(np.mean(list(per_pathway.values()))), per_pathway


def graph_neighborhood_ratio(
    graph_dir: str,
    gmt_path: str,
    *,
    max_pathway_genes: int = MAX_PATHWAY_GENES,
    seed: int = RANDOM_SEED,
) -> Dict[str, float]:
    """Intrinsic eval over a PRECOMPUTED kNN graph (a finalized
    ``knn_graph`` batch artifact, :func:`gene2vec_tpu.batch.artifact
    .load_graph`): the fraction of each gene's k nearest neighbors
    that share a pathway with it, against the same fraction for
    degree-matched random neighbor sets.

    The cosine-ratio :func:`target_function` needs the raw matrix;
    this one needs only the graph — the shape the serve fleet's batch
    plane exports — so the fleet's retrieval quality (including any
    ANN approximation) is measured exactly as served, not recomputed
    from the checkpoint.

    Returns ``{"neighbor_hit_rate", "random_hit_rate", "ratio",
    "genes_scored", "k"}``; raises ``ValueError`` when no graph gene
    appears in any pathway (wrong .gmt for this vocab)."""
    from gene2vec_tpu.batch.artifact import load_graph

    tokens, ids, _scores, meta = load_graph(graph_dir)
    pathways = load_gmt(gmt_path, max_pathway_genes)
    member: Dict[str, set] = {}
    for name, genes in pathways.items():
        for g in genes:
            member.setdefault(g, set()).add(name)
    token_member = [member.get(t) for t in tokens]
    k = ids.shape[1]
    rng = random.Random(seed)
    v = len(tokens)
    hits = rand_hits = 0
    scored = 0
    for row, m in enumerate(token_member):
        if not m:
            continue
        scored += 1
        for j in range(k):
            other = token_member[int(ids[row, j])]
            if other and not m.isdisjoint(other):
                hits += 1
        for _ in range(k):
            other = token_member[rng.randrange(v)]
            if other and not m.isdisjoint(other):
                rand_hits += 1
    if scored == 0:
        raise ValueError(
            "no graph gene appears in any pathway (vocab/.gmt mismatch)"
        )
    neighbor_rate = hits / (scored * k)
    random_rate = rand_hits / (scored * k)
    return {
        "neighbor_hit_rate": neighbor_rate,
        "random_hit_rate": random_rate,
        "ratio": (
            neighbor_rate / random_rate if random_rate > 0
            else float("inf")
        ),
        "genes_scored": scored,
        "k": k,
        "iteration": int(meta.get("iteration", -1)),
    }


def random_pair_similarity(
    tokens: Sequence[str],
    matrix: np.ndarray,
    *,
    num_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    """Mean cosine over all pairs of ``num_genes`` randomly chosen genes,
    with the reference's exact RNG recipe: python ``random.seed(seed)`` +
    ``random.shuffle`` of the emb-file gene order, take the first 1000
    (``src/evaluation_target_function.py:44-47``)."""
    gene_list = list(tokens)
    rng = random.Random()
    rng.seed(seed)
    rng.shuffle(gene_list)
    chosen = gene_list[:num_genes]
    if len(chosen) < 2:
        raise ValueError("embedding too small for random-pair denominator")
    token_to_id = {t: i for i, t in enumerate(tokens)}
    idx = [token_to_id[g] for g in chosen]
    unit = _unit_rows(np.asarray(matrix, dtype=np.float64))
    return mean_pairwise_cosine(unit[idx])
