"""Intrinsic evaluation — the "target function".

score = (mean intra-pathway cosine similarity) / (mean random-pair cosine
similarity), the de-facto correctness oracle for trained embeddings
(``src/evaluation_target_function.py:16-60``).  Semantics preserved:

* MSigDB ``.gmt`` pathways with more than 50 genes are skipped — the
  reference keeps lines with ≤52 tab fields: name, url, ≤50 genes
  (``src/evaluation_target_function.py:5-14``);
* pathways contribute only genes present in the embedding; pathways with
  <2 present genes are skipped (``combinations`` yields nothing);
* the denominator shuffles the embedding's gene list with
  ``random.seed(35)`` and averages all C(1000, 2) pair similarities
  (``src/evaluation_target_function.py:44-50``).

The reference computes this with an O(V) list-scan membership test per gene
and a Python loop over every pair (SURVEY §2.2 #14).  Here each pathway's
mean pairwise cosine collapses to one norm: with unit rows u_i,

    mean_{i<j} u_i·u_j = (‖Σ_i u_i‖² − n) / (n (n − 1)),

so the whole evaluation is one row-normalization plus a segment-sum — no
per-pair work at all.
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Tuple

import numpy as np

from gene2vec_tpu.io.emb_io import load_embedding_any

MAX_PATHWAY_GENES = 50
RANDOM_PAIR_GENES = 1000
RANDOM_SEED = 35


def load_gmt(path: str, max_genes: int = MAX_PATHWAY_GENES) -> Dict[str, List[str]]:
    """Pathway name → gene list from an MSigDB ``.gmt`` file (tab-separated:
    name, url, genes…), keeping pathways with at most ``max_genes`` genes."""
    pathways: Dict[str, List[str]] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            fields = line.rstrip("\n").split("\t")
            if len(fields) < 3 or len(fields) > max_genes + 2:
                continue
            pathways[fields[0]] = [g for g in fields[2:] if g]
    return pathways


def _unit_rows(matrix: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(matrix, axis=1, keepdims=True)
    return matrix / np.maximum(norms, 1e-12)


def mean_pairwise_cosine(unit: np.ndarray) -> float:
    """Mean over all C(n,2) pairwise cosine similarities of unit rows,
    via the sum-of-vectors identity (exact, no pair loop)."""
    n = unit.shape[0]
    if n < 2:
        raise ValueError("need at least 2 rows")
    s = unit.sum(axis=0)
    return float((s @ s - n) / (n * (n - 1)))


def target_function(
    emb_path: str,
    gmt_path: str,
    *,
    max_pathway_genes: int = MAX_PATHWAY_GENES,
    num_random_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    """The reference's ``targetFunc`` on any supported embedding file."""
    tokens, matrix = load_embedding_any(emb_path)
    pathways = load_gmt(gmt_path, max_pathway_genes)
    return target_function_arrays(
        tokens,
        matrix,
        pathways,
        num_random_genes=num_random_genes,
        seed=seed,
    )


def target_function_arrays(
    tokens: Sequence[str],
    matrix: np.ndarray,
    pathways: Dict[str, List[str]],
    *,
    num_random_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    numerator, _ = pathway_similarities(tokens, matrix, pathways)
    denominator = random_pair_similarity(
        tokens, matrix, num_genes=num_random_genes, seed=seed
    )
    return numerator / denominator


def pathway_similarities(
    tokens: Sequence[str],
    matrix: np.ndarray,
    pathways: Dict[str, List[str]],
) -> Tuple[float, Dict[str, float]]:
    """(mean over pathways, per-pathway mean intra-pathway cosine)."""
    token_to_id = {t: i for i, t in enumerate(tokens)}
    unit = _unit_rows(np.asarray(matrix, dtype=np.float64))
    per_pathway: Dict[str, float] = {}
    for name, genes in pathways.items():
        idx = [token_to_id[g] for g in genes if g in token_to_id]
        if len(idx) < 2:
            continue
        per_pathway[name] = mean_pairwise_cosine(unit[idx])
    if not per_pathway:
        raise ValueError("no pathway had ≥2 genes present in the embedding")
    return float(np.mean(list(per_pathway.values()))), per_pathway


def random_pair_similarity(
    tokens: Sequence[str],
    matrix: np.ndarray,
    *,
    num_genes: int = RANDOM_PAIR_GENES,
    seed: int = RANDOM_SEED,
) -> float:
    """Mean cosine over all pairs of ``num_genes`` randomly chosen genes,
    with the reference's exact RNG recipe: python ``random.seed(seed)`` +
    ``random.shuffle`` of the emb-file gene order, take the first 1000
    (``src/evaluation_target_function.py:44-47``)."""
    gene_list = list(tokens)
    rng = random.Random()
    rng.seed(seed)
    rng.shuffle(gene_list)
    chosen = gene_list[:num_genes]
    if len(chosen) < 2:
        raise ValueError("embedding too small for random-pair denominator")
    token_to_id = {t: i for i, t in enumerate(tokens)}
    idx = [token_to_id[g] for g in chosen]
    unit = _unit_rows(np.asarray(matrix, dtype=np.float64))
    return mean_pairwise_cosine(unit[idx])
