"""Fleet-sharded serving gate: BENCH_SHARD vs budgets.json ``shard``.

``python scripts/chaos_drill.py --only shard --shard-out
BENCH_SHARD_r*.json`` stamps the sharded-serving record — the 10M-row
scatter-merge bench (recall@10 vs the exact oracle with all shards up,
degraded recall with one shard removed, merged p99) plus the HTTP
chaos drill facts (availability and answer integrity under a SIGKILLed
shard, swap-under-load, slow-loris shard).  This pass re-checks the
NEWEST committed record against the ``scatter`` entry of the ``shard``
budgets section every ``cli.analyze`` run.

Rules (the passes_ann / passes_fleet shape — jax-free, I/O-only, so it
rides the DEFAULT tier):

* no ``BENCH_SHARD_r*`` artifact at all → *info* (a fresh checkout
  must not fail lint before its first drill);
* the budget pins the bench **measurement recipe** (rows, dim, shards,
  k, queries, index, nprobe, rescore_mult, clusters): a record
  measured at a smaller table or with looser knobs gates hard — a
  64k-row smoke must never stand in for the 10M gate;
* all-shards-up recall@10 below ``min_recall_at_10``, merged p99 over
  ``max_p99_ms``, or degradation NOT tracking the dead shard's row
  fraction (|recall_drop − row_fraction| > tolerance) gates hard;
* the drill half gates availability, zero server 5xx (degraded answers
  must be flagged 200s, never failures), zero wrong / mixed-iteration
  answers (the epoch fence under swap-under-load), and retry
  amplification (one shared token bucket across the fan-out);
* any budgeted quantity missing from the record gates like a
  violation — dropping the key must never be the way to pass.

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (shared with
the other bench gates so staged fixture dirs work uniformly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.passes_perf import perf_root

_PASS = "shard-scatter-budget"

_RECIPE_KEYS = ("rows", "dim", "shards", "k", "queries", "nprobe",
                "rescore_mult", "clusters")


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_shard_bench(root: str) -> Optional[str]:
    """Newest ``BENCH_SHARD_*`` under ``root`` (highest round wins,
    mtime breaks ties) — the round convention every gate follows."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched is not None and matched[0] == "shard":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime,
                               path))
    if not candidates:
        return None
    return max(candidates)[2]


def shard_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the newest committed shard bench against ``shard.scatter``."""
    budget = load_budgets(budgets_path).get("shard", {}).get("scatter")
    if not isinstance(budget, dict):
        return []
    root = root or perf_root()
    path = _newest_shard_bench(root)
    if path is None:
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path="BENCH_SHARD",
            message=(
                "no sharded-serving bench recorded yet "
                "(BENCH_SHARD_r*.json missing); run `python "
                "scripts/chaos_drill.py --only shard --shard-out "
                "BENCH_SHARD_rNN.json` (it reads the pinned recipe "
                "from budgets.json 'shard') to stamp one"
            ),
        )]
    label = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable shard bench: {e}",
        )]

    problems: List[str] = []
    data: Dict = {"budget": "shard.scatter"}
    section = doc.get("shard")
    section = section if isinstance(section, dict) else {}
    bench = section.get("bench")
    bench = bench if isinstance(bench, dict) else {}
    drill = section.get("drill")
    drill = drill if isinstance(drill, dict) else {}
    if not bench:
        problems.append("record has no shard.bench section")
    if not drill:
        problems.append("record has no shard.drill section")

    # -- the bench half: recipe-pinned recall/latency/degradation ------
    pinned_recipe = budget.get("recipe") or {}
    for key in _RECIPE_KEYS:
        pinned = _get(pinned_recipe, key)
        if pinned is None:
            continue
        measured = _get(bench, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"bench.{key} missing from the record")
        elif measured != pinned:
            problems.append(
                f"bench measured with {key}={measured:g} but the "
                f"budget pins {key}={pinned:g} — re-run the full "
                "(non-smoke) shard drill"
            )
    want_index = pinned_recipe.get("index")
    if want_index is not None and bench.get("index") != want_index:
        problems.append(
            f"bench measured with index={bench.get('index')!r} but "
            f"the budget pins {want_index!r}"
        )

    recall = _get(bench, "recall_at_10")
    floor = _get(budget, "min_recall_at_10")
    data["recall_at_10"] = recall
    data["min_recall_at_10"] = floor
    if floor is not None:
        if recall is None:
            problems.append(
                "bench.recall_at_10 missing from the record"
            )
        elif recall < floor:
            problems.append(
                f"all-shards-up recall@10 {recall:g} < budget "
                f"{floor:g} — the cross-process merge is losing true "
                "neighbors"
            )
    p99 = _get(bench, "p99_ms")
    ceiling = _get(budget, "max_p99_ms")
    data["p99_ms"] = p99
    data["max_p99_ms"] = ceiling
    if ceiling is not None:
        if p99 is None:
            problems.append("bench.p99_ms missing from the record")
        elif p99 > ceiling:
            problems.append(
                f"merged p99 {p99:g} ms > budget {ceiling:g} ms at "
                "the 10M-row geometry"
            )
    # graceful degradation is MEASURED: killing one shard must cost
    # recall roughly that shard's row fraction — more means the merge
    # loses extra answers, (much) less means the "dead" shard leaked in
    tol = _get(budget, "recall_degradation_tolerance")
    degraded = _get(bench, "degraded_recall_at_10")
    frac = _get(bench, "dead_shard_row_fraction")
    data["degraded_recall_at_10"] = degraded
    data["dead_shard_row_fraction"] = frac
    if tol is not None:
        if degraded is None or frac is None or recall is None:
            problems.append(
                "bench degraded_recall_at_10 / dead_shard_row_fraction "
                "missing from the record"
            )
        elif abs((recall - degraded) - frac) > tol:
            problems.append(
                f"recall drop with one shard dead ({recall:g} -> "
                f"{degraded:g}) does not track its row fraction "
                f"{frac:g} within ±{tol:g} — degradation is not "
                "graceful"
            )

    # -- the drill half: availability + answer integrity ---------------
    for key, kind in (
        ("availability", "min"),
        ("retry_amplification", "max"),
    ):
        bound = _get(budget, f"{kind}_{key}")
        if bound is None:
            continue
        v = _get(drill, key)
        data[key] = v
        data[f"{kind}_{key}"] = bound
        if v is None:
            problems.append(f"drill.{key} missing from the record")
        elif kind == "min" and v < bound:
            problems.append(
                f"drill {key} {v:g} < budget {bound:g}"
            )
        elif kind == "max" and v > bound:
            problems.append(
                f"drill {key} {v:g} > budget {bound:g}"
            )
    for key in ("server_5xx", "wrong_answers",
                "mixed_iteration_answers"):
        ceiling = _get(budget, f"max_{key}")
        if ceiling is None:
            continue
        v = _get(drill, key)
        data[key] = v
        if v is None:
            problems.append(f"drill.{key} missing from the record")
        elif v > ceiling:
            problems.append(
                f"{int(v)} {key.replace('_', ' ')} recorded (budget "
                f"{int(ceiling)}) — "
                + ("a dead shard must degrade, never 5xx"
                   if key == "server_5xx"
                   else "answer integrity is broken in the shard path")
            )
    http_shards = _get(budget, "http_shards")
    if http_shards is not None:
        got = _get(drill, "shards")
        data["http_shards"] = got
        if got is None:
            problems.append("drill.shards missing from the record")
        elif got != http_shards:
            problems.append(
                f"drill ran {got:g} shards but the budget pins "
                f"{http_shards:g}"
            )

    # -- the failover half: replicated shards (replica groups) ----------
    # A dead replica with a live SIBLING must cost nothing: zero
    # degraded answers, availability intact, p99 bounded — and with the
    # whole group dead the PR-13 degraded contract must be unchanged.
    fo_budget = budget.get("failover")
    if isinstance(fo_budget, dict):
        fo = drill.get("failover")
        fo = fo if isinstance(fo, dict) else {}
        if not fo:
            problems.append(
                "record has no drill.failover section — re-run the "
                "shard drill (it now includes the replicated-shard "
                "scenario)"
            )
        rps = _get(fo_budget, "replicas_per_shard")
        if rps is not None:
            got = _get(fo, "replicas_per_shard")
            data["failover_replicas_per_shard"] = got
            if got is None:
                problems.append(
                    "drill.failover.replicas_per_shard missing from "
                    "the record"
                )
            elif got != rps:
                problems.append(
                    f"failover drill ran {got:g} replicas per shard "
                    f"but the budget pins {rps:g}"
                )
        fo_avail = _get(fo, "availability")
        fo_floor = _get(fo_budget, "min_availability")
        data["failover_availability"] = fo_avail
        if fo_floor is not None:
            if fo_avail is None:
                problems.append(
                    "drill.failover.availability missing from the "
                    "record"
                )
            elif fo_avail < fo_floor:
                problems.append(
                    f"failover availability {fo_avail:g} < budget "
                    f"{fo_floor:g} — a sibling was live the whole time"
                )
        deg = _get(fo, "degraded_responses")
        deg_max = _get(fo_budget, "max_degraded_with_live_replica")
        data["failover_degraded_responses"] = deg
        if deg_max is not None:
            if deg is None:
                problems.append(
                    "drill.failover.degraded_responses missing from "
                    "the record"
                )
            elif deg > deg_max:
                problems.append(
                    f"{int(deg)} degraded responses with a LIVE "
                    f"sibling (budget {int(deg_max)}) — failover must "
                    "absorb a single replica death entirely"
                )
        fo_p99 = _get(fo, "p99_ms")
        p99_max = _get(fo_budget, "max_failover_p99_ms")
        data["failover_p99_ms"] = fo_p99
        if p99_max is not None:
            if fo_p99 is None:
                problems.append(
                    "drill.failover.p99_ms missing from the record"
                )
            elif fo_p99 > p99_max:
                problems.append(
                    f"failover-window p99 {fo_p99:g} ms > budget "
                    f"{p99_max:g} ms — failing over eats the deadline"
                )
        both = fo.get("both_dead")
        both = both if isinstance(both, dict) else {}
        both_min = _get(fo_budget, "min_both_dead_degraded")
        both_deg = _get(both, "degraded_responses")
        data["both_dead_degraded_responses"] = both_deg
        if both_min is not None:
            if both_deg is None:
                problems.append(
                    "drill.failover.both_dead.degraded_responses "
                    "missing from the record"
                )
            elif both_deg < both_min:
                problems.append(
                    f"only {int(both_deg)} degraded responses with the "
                    "whole replica group dead (budget >= "
                    f"{int(both_min)}) — the both-dead window never "
                    "landed, the degraded contract went unverified"
                )

    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "shard bench record violates budget 'shard.scatter': "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"sharded serving within budget 'shard.scatter': "
            f"recall@10 {recall:g} all-up / {degraded:g} one-dead "
            f"(row fraction {frac:g}), p99 {p99:g} ms, drill "
            f"availability {data.get('availability')}"
        ),
        data=data,
    )]
