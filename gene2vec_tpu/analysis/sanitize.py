"""Sanitizer harness for the native/ kernels (tier 3 of graftcheck).

Builds the ``*_asan.so`` / ``*_ubsan.so`` / ``*_tsan.so`` variants via
``make -C native <kind>`` and runs the pairio + Hogwild parity workload
in a **subprocess** with the right runtime environment:

* ASAN must be the first DSO in the process, so the child runs under
  ``LD_PRELOAD=libasan.so`` (CPython itself is uninstrumented — fine:
  the interceptors still wrap malloc/str* globally, which is exactly
  what caught the pairio tokens-blob over-read this subsystem was built
  around);
* UBSAN links its shared runtime into the .so and needs no preload;
  ``-fno-sanitize-recover`` turns the first report into an abort, so a
  nonzero child exit IS the finding;
* TSAN needs ``LD_PRELOAD=libtsan.so`` plus the intended-race
  suppressions in native/tsan.supp (Hogwild's lock-free table updates
  are the algorithm, not a bug — see that file).

The workload itself (:data:`PARITY_SCRIPT`) re-points the production
ctypes wrappers at the sanitized libraries, so the exact code paths
tier-1 trusts are the ones being checked.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from typing import List, Optional, Tuple

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.runner import REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")

KINDS = ("asan", "ubsan", "tsan")

_RUNTIME_LIB = {"asan": "libasan.so", "ubsan": None, "tsan": "libtsan.so"}

_OPTIONS_ENV = {
    "asan": ("ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1"),
    "ubsan": ("UBSAN_OPTIONS", "halt_on_error=1:print_stacktrace=1"),
    "tsan": (
        "TSAN_OPTIONS",
        f"suppressions={os.path.join(NATIVE_DIR, 'tsan.supp')}:"
        "halt_on_error=1:exitcode=66",
    ),
}

#: run in the child: pairio parity (native vs pure-Python reader, messy
#: corpus) + a multithreaded Hogwild epoch, against the sanitized .so
PARITY_SCRIPT = r"""
import os, sys, tempfile
import numpy as np

kind = sys.argv[1]
repo = sys.argv[2]
sys.path.insert(0, repo)
os.environ["GENE2VEC_TPU_NO_NATIVE_BUILD"] = "1"  # libs are prebuilt

from gene2vec_tpu.io import native_pairio
from gene2vec_tpu.sgns import native_backend

native_pairio._LIB_PATH = os.path.join(
    repo, "native", f"libpairio_{kind}.so"
)
native_backend._LIB_PATH = os.path.join(
    repo, "native", f"libsgns_hogwild_{kind}.so"
)

# -- pairio parity (the messy-lines fixture that used to flake) -------------
with tempfile.TemporaryDirectory() as d:
    with open(os.path.join(d, "a.txt"), "wb") as f:
        f.write(
            b"A B\n\nC\nD E F\nB\tA\nG\xe9NE1 G\xe9NE2\n  A   B  \n"
        )
    with open(os.path.join(d, "b.txt"), "wb") as f:
        f.write(b"H I\nI H\nH I\n" * 50)
    from gene2vec_tpu.io.pair_reader import iter_pair_files, load_corpus

    vp, pp = load_corpus(d, "txt", use_native=False)
    for _ in range(20):  # heap churn across repeated loads
        vn, pn = native_pairio.load_corpus(iter_pair_files(d, "txt"))
    assert vn.id_to_token == vp.id_to_token, "pairio token parity"
    assert np.array_equal(np.asarray(vn.counts), np.asarray(vp.counts))
    assert np.array_equal(pn, pp), "pairio pair parity"

    # strict-cp1252 rejection path (the -3 early return)
    with open(os.path.join(d, "bad.txt"), "wb") as f:
        f.write(b"GENE1 GENE2\nGEN\x81E3 X\n")
    try:
        native_pairio.load_corpus([os.path.join(d, "bad.txt")])
        raise SystemExit("expected UnicodeDecodeError")
    except UnicodeDecodeError:
        pass

# -- Hogwild epoch under threads -------------------------------------------
from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab

rng = np.random.RandomState(0)
# GRAFTCHECK_SMALL shrinks the epoch for unsuppressed-TSAN auditing,
# where every racy table access logs a report (full size would spend
# minutes printing)
V, N = (200, 20000) if not os.environ.get("GRAFTCHECK_SMALL") else (50, 400)
pairs = rng.randint(0, V, (N, 2)).astype(np.int32)
counts = np.bincount(pairs.reshape(-1), minlength=V).astype(np.int64)
corpus = PairCorpus(Vocab([f"G{i}" for i in range(V)], counts), pairs)

cfg = SGNSConfig(dim=32, negatives=5)
tr = native_backend.HogwildSGNSTrainer(corpus, cfg, n_threads=4)
params = tr.init()
before = np.array(params.emb, copy=True)
params, loss = tr.train_epoch(params, seed=1)
assert np.isfinite(loss), f"hogwild loss not finite: {loss}"
assert not np.array_equal(before, np.asarray(params.emb)), "tables unchanged"

hs = native_backend.HogwildHSTrainer(
    corpus, SGNSConfig(dim=32, objective="cbow_hs"), n_threads=4
)
hs_params, hs_loss = hs.train_epoch(hs.init(), seed=1)
assert np.isfinite(hs_loss), f"hs loss not finite: {hs_loss}"
print("PARITY_OK", kind, file=sys.stderr)
"""


def _compiler() -> str:
    """The compiler native/Makefile will use (its ``CXX ?=`` default)."""
    return os.environ.get("CXX", "g++").split()[0]


def runtime_lib_path(kind: str) -> Optional[str]:
    """Absolute path of the sanitizer runtime to LD_PRELOAD, None when
    the kind needs no preload, or "" when the toolchain lacks it."""
    name = _RUNTIME_LIB[kind]
    if name is None:
        return None
    cxx = _compiler()
    if "clang" in os.path.basename(cxx):
        # clang's runtimes (libclang_rt.<san>-<arch>.so) have a different
        # preload story; discovery here knows the GNU layout only — report
        # unavailable (info skip) rather than preload a mismatched GCC
        # runtime and falsely gate on the resulting startup abort
        return ""
    try:
        out = subprocess.run(
            [cxx, f"-print-file-name={name}"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except Exception:
        return ""
    # the compiler echoes the bare name back when it cannot find the file
    return out if os.path.isabs(out) and os.path.exists(out) else ""


def build(kind: str, timeout: int = 300) -> Tuple[bool, str]:
    """``make -C native <kind>`` → (ok, detail).  ``detail`` carries the
    make stderr tail on failure: a broken sanitized build must surface
    (and gate) as build breakage, never read as a missing toolchain."""
    try:
        proc = subprocess.run(
            ["make", "-C", NATIVE_DIR, kind],
            capture_output=True, text=True, timeout=timeout,
        )
    except Exception as e:
        return False, f"make {kind} did not run: {e}"
    if proc.returncode != 0:
        return False, (
            f"make {kind} failed (exit {proc.returncode}); stderr tail:\n"
            + proc.stderr[-4000:]
        )
    missing = [
        f"{stem}_{kind}.so"
        for stem in ("libpairio", "libsgns_hogwild")
        if not os.path.exists(os.path.join(NATIVE_DIR, f"{stem}_{kind}.so"))
    ]
    if missing:
        return False, f"make {kind} exited 0 but did not produce {missing}"
    return True, ""


def toolchain_available(kind: str) -> bool:
    """Compiler + sanitizer runtime present.  Deliberately does NOT
    attempt the build: on a machine with a working toolchain a failed
    sanitized build is a gating finding (see :func:`sanitizer_findings`)
    / test failure, not a silent skip."""
    if shutil.which(_compiler()) is None:
        return False
    return runtime_lib_path(kind) != ""


def _libstdcxx_path() -> str:
    try:
        out = subprocess.run(
            [_compiler(), "-print-file-name=libstdc++.so.6"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
        return out if os.path.isabs(out) and os.path.exists(out) else ""
    except Exception:
        return ""


def run_parity(
    kind: str,
    timeout: int = 600,
    options: Optional[str] = None,
    extra_env: Optional[dict] = None,
) -> subprocess.CompletedProcess:
    """Run :data:`PARITY_SCRIPT` in a sanitized child process.
    ``options`` overrides the default ``*SAN_OPTIONS`` (e.g. an
    unsuppressed TSAN audit); ``extra_env`` adds child-only variables
    (e.g. ``GRAFTCHECK_SMALL``) without mutating the caller's env."""
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    # pin the CHILD to CPU (it imports jax transitively and must not
    # claim an accelerator) — scoped here so the calling process's env
    # is never mutated by the sanitizer tier
    env.setdefault("JAX_PLATFORMS", "cpu")
    preload = runtime_lib_path(kind)
    if preload:
        # co-preload libstdc++: the sanitizer's __cxa_throw interceptor
        # must resolve the real symbol at startup, or the first C++
        # exception thrown from an uninstrumented late-loaded DSO
        # (jaxlib's MLIR bindings) aborts with an interceptor CHECK
        stdcxx = _libstdcxx_path()
        env["LD_PRELOAD"] = f"{preload} {stdcxx}".strip()
    opt_key, opt_val = _OPTIONS_ENV[kind]
    env[opt_key] = opt_val if options is None else options
    argv = [sys.executable, "-c", PARITY_SCRIPT, kind, REPO_ROOT]
    try:
        return subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO_ROOT,
        )
    except subprocess.TimeoutExpired as e:
        # a hung instrumented child is a gating failure, not an internal
        # analyzer crash — synthesize a nonzero result carrying whatever
        # the child said before the clock ran out
        def _text(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")

        return subprocess.CompletedProcess(
            argv, returncode=124, stdout=_text(e.stdout),
            stderr=_text(e.stderr)
            + f"\n[graftcheck] {kind} parity child timed out after {timeout}s",
        )


def _tsan_supp_patterns() -> List[str]:
    """The symbol patterns in native/tsan.supp (``race:X`` lines)."""
    patterns: List[str] = []
    try:
        with open(os.path.join(NATIVE_DIR, "tsan.supp"), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#") and ":" in line:
                    patterns.append(line.split(":", 1)[1])
    except OSError:
        pass
    return patterns


def tsan_control_findings(timeout: int = 600) -> List[Finding]:
    """The unsuppressed control run: the same Hogwild workload under
    TSAN *without* native/tsan.supp MUST report the intended lock-free
    table races — they are the algorithm.  Zero reports means the
    suppressed green run is vacuous (serialized workers, uninstrumented
    build, or a supp pattern that now swallows everything); a supp
    pattern matching no control report is a stale entry that would hide
    a future real race symbolizing under that name.  Shrinks the epoch
    via ``GRAFTCHECK_SMALL`` — unsuppressed TSAN logs every racy access,
    and the full-size epoch would spend minutes printing."""
    label = "sanitizer:tsan-control"
    proc = run_parity(
        "tsan", timeout=timeout,
        options="halt_on_error=0:exitcode=66",
        extra_env={"GRAFTCHECK_SMALL": "1"},
    )
    stderr = proc.stderr or ""
    if proc.returncode == 124:
        return [Finding(
            pass_id="sanitizer",
            path=label,
            message="unsuppressed tsan control run timed out",
            data={"stderr_tail": stderr[-4000:]},
        )]
    if "WARNING: ThreadSanitizer: data race" not in stderr:
        return [Finding(
            pass_id="sanitizer",
            severity="warning",
            path=label,
            message=(
                "unsuppressed tsan control run reported NO data races — "
                "the Hogwild workers are no longer racing (serialized "
                "build?) or TSAN is not engaging, so the suppressed "
                "green run proves nothing; native/tsan.supp may be stale"
            ),
            data={"stderr_tail": stderr[-4000:]},
        )]
    findings: List[Finding] = []
    for pattern in _tsan_supp_patterns():
        if pattern in stderr:
            continue
        findings.append(Finding(
            pass_id="sanitizer",
            severity="warning",
            path=label,
            message=(
                f"tsan.supp entry '{pattern}' matched no report in the "
                "unsuppressed control run — a stale suppression would "
                "hide a future real race symbolizing under that name"
            ),
            data={"pattern": pattern},
        ))
    if not findings:
        findings.append(Finding(
            pass_id="sanitizer",
            severity="info",
            path=label,
            message=(
                "unsuppressed control run reports the intended Hogwild "
                "races and every tsan.supp entry matches — the "
                "suppressions are load-bearing"
            ),
        ))
    return findings


def sanitizer_findings(kinds=("asan", "ubsan")) -> List[Finding]:
    """Build + run each requested sanitizer; failures carry the tail of
    the child's stderr (the sanitizer report).  A missing toolchain is an
    info skip; a *failed build on a present toolchain* is a gating
    finding — otherwise build breakage would silently disable the
    memory-safety gate while it reports green."""
    findings: List[Finding] = []
    for kind in kinds:
        label = f"sanitizer:{kind}"
        if not toolchain_available(kind):
            findings.append(Finding(
                pass_id="sanitizer",
                severity="info",
                path=label,
                message=f"{kind} toolchain unavailable; skipped",
            ))
            continue
        ok, detail = build(kind)
        if not ok:
            findings.append(Finding(
                pass_id="sanitizer",
                path=label,
                message=(
                    f"{kind} instrumented build failed — the sanitizer "
                    f"gate did not run: {detail}"
                ),
            ))
            continue
        proc = run_parity(kind)
        if proc.returncode != 0:
            findings.append(Finding(
                pass_id="sanitizer",
                path=label,
                message=(
                    f"{kind} parity run failed (exit {proc.returncode})"
                ),
                data={"stderr_tail": proc.stderr[-4000:]},
            ))
        else:
            findings.append(Finding(
                pass_id="sanitizer",
                severity="info",
                path=label,
                message=f"{kind} parity run clean",
            ))
            if kind == "tsan":
                # the suppressed run was green — prove it means
                # something: the unsuppressed control binary must still
                # report the intended Hogwild races
                findings.extend(tsan_control_findings())
    return findings
