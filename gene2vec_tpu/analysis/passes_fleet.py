"""Fleet availability budget gate: BENCH_FLEET vs budgets.json.

The fleet chaos drill (``scripts/chaos_drill.py``, phase ``fleet``)
records client-observed availability, answer-integrity counts, and
retry amplification into ``BENCH_FLEET_r08.json``.  This pass re-checks
that committed record against the ``fleet`` section of ``budgets.json``
every ``cli.analyze`` run, so an availability regression — a drill
rerun stamping worse numbers, or a budget quietly loosened — fails the
analyzer exactly like a collective-bytes regression does.

Deliberately jax-free and I/O-only (two small JSON reads): it runs in
the default tier, not behind ``--hlo``.  A missing bench file is an
*info* finding, not a gate — a fresh checkout must not fail lint before
its first drill — but a bench file that exists and violates the budget
gates hard.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

BENCH_FLEET_PATH = os.path.join(REPO_ROOT, "BENCH_FLEET_r08.json")

_PASS = "fleet-availability-budget"


def fleet_budget_findings(
    bench_path: str = BENCH_FLEET_PATH,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the recorded fleet drill results against the budget."""
    budgets: Dict = load_budgets(budgets_path).get("fleet", {})
    if not budgets:
        return []
    label = os.path.basename(bench_path)
    if not os.path.exists(bench_path):
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no fleet bench recorded yet ({label} missing); run "
                "`python scripts/chaos_drill.py --only fleet --fleet-out "
                f"{label}` to stamp one"
            ),
        )]
    try:
        with open(bench_path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable fleet bench: {e}",
        )]

    findings: List[Finding] = []
    for name, budget in budgets.items():
        if name.startswith("_"):
            continue
        section = bench.get("fleet") or bench.get("phases", {}).get("fleet")
        if not isinstance(section, dict):
            findings.append(Finding(
                pass_id=_PASS,
                path=label,
                message=(
                    f"{label} has no 'fleet' results section to check "
                    f"against budget {name!r}"
                ),
            ))
            continue
        findings.extend(_check_one(name, budget, section, label))
    return findings


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _check_one(
    name: str, budget: Dict, section: Dict, label: str
) -> List[Finding]:
    availability = _get(section, "availability")
    amplification = _get(section, "retry_amplification")
    mixed = _get(section, "mixed_iteration_answers")
    wrong = _get(section, "wrong_answers")
    data = {
        "budget": name,
        "availability": availability,
        "min_availability": budget["min_availability"],
        "retry_amplification": amplification,
        "max_retry_amplification": budget["max_retry_amplification"],
        "mixed_iteration_answers": mixed,
        "wrong_answers": wrong,
    }
    # every budgeted quantity must be PRESENT: a record missing a field
    # must gate like a violation, or dropping the key becomes the way
    # to pass (availability is checked the same way below)
    problems: List[str] = []
    if availability is None:
        problems.append("availability missing from the bench record")
    elif availability < float(budget["min_availability"]):
        problems.append(
            f"availability {availability:.4f} < budget "
            f"{budget['min_availability']}"
        )
    if amplification is None:
        problems.append(
            "retry_amplification missing from the bench record"
        )
    elif amplification > float(budget["max_retry_amplification"]):
        problems.append(
            f"retry amplification {amplification:.3f} > budget "
            f"{budget['max_retry_amplification']} (retries are "
            "multiplying load instead of being budgeted)"
        )
    # each answer-integrity count has its OWN budget key: sharing one
    # ceiling would let loosening the mixed-answer budget silently
    # loosen the wrong-answer gate too
    for what, count, ceiling in (
        ("mixed-iteration", mixed,
         float(budget.get("max_mixed_iteration_answers", 0))),
        ("wrong", wrong, float(budget.get("max_wrong_answers", 0))),
    ):
        if count is None:
            problems.append(
                f"{what.replace('-', '_')}_answers missing from the "
                "bench record"
            )
        elif count > ceiling:
            problems.append(
                f"{int(count)} {what} answer(s) recorded (budget "
                f"{int(ceiling)}) — answer integrity is broken "
                "somewhere in the serve path"
            )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                f"fleet drill record violates budget {name!r}: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"fleet availability {availability:.4f} within budget "
            f"{name!r} (>= {budget['min_availability']})"
        ),
        data=data,
    )]
