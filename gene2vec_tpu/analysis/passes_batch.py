"""Batch-plane budget gate: BENCH_BATCH vs budgets.json ``batch``.

``python scripts/chaos_drill.py --only batch --batch-out
BENCH_BATCH_r*.json`` stamps the offline analytics plane's record —
full-vocab kNN graph throughput through the live front door's
background lane, sampled recall@k vs the brute-force cosine oracle,
SIGKILL-resume bit-identity, the 1M-row sampled-query scaling
measurement, and the mixed-workload interactive p99 delta.  This pass
re-checks the NEWEST committed record against the ``graph`` entry of
the ``batch`` budgets section every ``cli.analyze`` run, so a batch
plane that quietly starts losing neighbors, breaking resume
bit-identity, or bleeding into the interactive SLO fails the analyzer
exactly like a collective-bytes regression does.

Rules (the passes_ann / passes_loop shape — jax-free, I/O-only, so it
rides the DEFAULT tier):

* no ``BENCH_BATCH_r*`` artifact at all → *info* (a fresh checkout
  must not fail lint before its first drill);
* the budget pins the **measurement recipe** (rows/dim/k at both
  geometries, shards, chunk_rows, query sample, batch tenant weight):
  a record measured off-recipe gates hard — throughput at k=2 must
  not pass a gate whose contract is k=10;
* graph recall@k below ``min_recall_at_10`` (24k, as served through
  the fleet) or ``min_recall_at_10_1m`` (the ivf scaling table)
  gates; a missing budgeted quantity gates like a violation —
  dropping the key must never be the way to pass;
* ``require_resume_bit_exact``: the SIGKILLed-and-resumed artifact
  must be byte-identical to the uninterrupted control;
* the mixed-workload interactive p99 delta must stay within
  ``max_p99_delta_frac`` **or** ``max_p99_delta_ms`` — either
  suffices, because a short window's p99 swings several ms between
  identical runs on this container's CPU and a fast baseline must not
  turn scheduler noise into a gate;
* a drill that stamped ``passed: false`` gates on its own verdict.

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (shared with
``passes_perf``/``passes_ann`` so staged fixture dirs work
uniformly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.passes_perf import perf_root

_PASS = "batch-graph-budget"

#: budget recipe key -> bench record recipe key (identical names; the
#: indirection exists so the pinning loop is data, not code)
_RECIPE_KEYS = (
    "rows_24k",
    "dim_24k",
    "k",
    "shards",
    "chunk_rows",
    "rows_1m",
    "dim_1m",
    "queries_1m",
    "batch_weight",
)


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    return float(v) if isinstance(v, (int, float)) else None


def _newest_batch_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_BATCH_*`` artifact under ``root`` (highest
    round wins, mtime breaks ties)."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched is not None and matched[0] == "batch":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime,
                               path))
    if not candidates:
        return None
    return max(candidates)[2]


def batch_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the newest committed batch drill against ``batch.graph``."""
    budget = load_budgets(budgets_path).get("batch", {}).get("graph")
    if not isinstance(budget, dict):
        return []
    root = root or perf_root()
    path = _newest_batch_bench(root)
    if path is None:
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path="BENCH_BATCH",
            message=(
                "no batch drill recorded yet (BENCH_BATCH_r*.json "
                "missing); run `python scripts/chaos_drill.py --only "
                "batch --batch-out BENCH_BATCH_rNN.json` (it reads the "
                "pinned recipe from budgets.json 'batch') to stamp one"
            ),
        )]
    label = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable batch drill record: {e}",
        )]

    problems: List[str] = []
    data: Dict = {"budget": "batch.graph"}
    section = bench.get("batch")
    section = section if isinstance(section, dict) else {}

    recipe = section.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    for key in _RECIPE_KEYS:
        pinned = _get(budget, key)
        if pinned is None:
            continue
        measured = _get(recipe, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(
                f"recipe.{key} missing from the drill record"
            )
        elif measured != pinned:
            problems.append(
                f"drill measured with {key}={measured:g} but the "
                f"budget pins {key}={pinned:g} — re-run the batch "
                "drill"
            )

    graph = section.get("graph_24k")
    graph = graph if isinstance(graph, dict) else {}
    floor = _get(budget, "min_recall_at_10")
    recall = _get(graph, "recall_at_10")
    data["recall_at_10"] = recall
    if floor is not None:
        if recall is None:
            problems.append(
                "graph_24k.recall_at_10 missing from the drill record"
            )
        elif recall < floor:
            problems.append(
                f"graph_24k.recall_at_10 {recall:g} < budget {floor:g} "
                "(the batch-built graph is losing true neighbors)"
            )
    rows_per_sec = _get(graph, "rows_per_sec")
    data["rows_per_sec"] = rows_per_sec
    if rows_per_sec is None:
        problems.append(
            "graph_24k.rows_per_sec missing from the drill record"
        )
    if _get(budget, "require_resume_bit_exact"):
        bit_exact = _get(graph, "resume_bit_exact")
        data["resume_bit_exact"] = bit_exact
        if not bit_exact:
            problems.append(
                "graph_24k.resume_bit_exact is not 1 — the SIGKILLed-"
                "and-resumed artifact diverged from the uninterrupted "
                "control"
            )

    floor_1m = _get(budget, "min_recall_at_10_1m")
    g1m = section.get("graph_1m")
    g1m = g1m if isinstance(g1m, dict) else {}
    if floor_1m is not None:
        recall_1m = _get(g1m, "recall_at_10")
        data["recall_at_10_1m"] = recall_1m
        if recall_1m is None:
            problems.append(
                "graph_1m.recall_at_10 missing from the drill record"
            )
        elif recall_1m < floor_1m:
            problems.append(
                f"graph_1m.recall_at_10 {recall_1m:g} < budget "
                f"{floor_1m:g}"
            )
        if _get(g1m, "rows_per_sec") is None:
            problems.append(
                "graph_1m.rows_per_sec missing from the drill record"
            )

    max_frac = _get(budget, "max_p99_delta_frac")
    max_ms = _get(budget, "max_p99_delta_ms")
    mixed = section.get("mixed")
    mixed = mixed if isinstance(mixed, dict) else {}
    if max_frac is not None or max_ms is not None:
        delta_frac = _get(mixed, "p99_delta_frac")
        delta_ms = _get(mixed, "p99_delta_ms")
        data["p99_delta_frac"] = delta_frac
        data["p99_delta_ms"] = delta_ms
        if delta_frac is None and delta_ms is None:
            problems.append(
                "mixed.p99_delta_frac / p99_delta_ms missing from the "
                "drill record — the SLO-protection claim is unmeasured"
            )
        else:
            frac_ok = (
                max_frac is not None and delta_frac is not None
                and delta_frac <= max_frac
            )
            ms_ok = (
                max_ms is not None and delta_ms is not None
                and delta_ms <= max_ms
            )
            if not (frac_ok or ms_ok):
                problems.append(
                    f"interactive p99 under batch load regressed by "
                    f"{delta_frac} ({delta_ms} ms) — outside BOTH "
                    f"max_p99_delta_frac {max_frac} and "
                    f"max_p99_delta_ms {max_ms}; the background lane "
                    "is eating the interactive SLO"
                )

    if bench.get("passed") is False:
        problems.append("the drill itself stamped passed=false")

    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "batch drill record violates budget 'batch.graph': "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"batch graph {data.get('rows_per_sec')} rows/s at recall "
            f"{data.get('recall_at_10')} (1M table "
            f"{data.get('recall_at_10_1m')}), p99 delta "
            f"{data.get('p99_delta_ms')} ms within budget "
            "'batch.graph'"
        ),
        data=data,
    )]
