"""Interprocedural thread-role and lock model for the concurrency tier.

The serve/fleet/loop planes are threaded Python: event-loop acceptors,
HandlerPool workers, the micro-batcher worker, fleet supervisors and
respawn monitors, registry watchers, shadow workers, async checkpoint
writers.  Their invariants (hot-swap-by-single-reference, queue handoff,
"never block the loop thread") were enforced only by tests and reviewer
memory; this module gives :mod:`passes_concurrency` the static facts it
needs to check them mechanically:

* **thread entry points** — ``threading.Thread(target=...)``, event-loop
  ``_on_*`` callbacks, ``HandlerPool.submit`` / ``submit_async`` /
  ``observers.append`` / ``on_done=`` callback registrations — each
  classified into a role (``loop`` / ``worker`` / ``monitor`` /
  ``writer``; unthreaded code is implicitly ``main``);
* a **conservative call graph** (``self.method()``, lexical bare names
  via :func:`astpass._scope_index`'s approach, typed attributes
  ``self.pool.submit`` where ``self.pool = HandlerPool(...)``, package
  imports) through which roles propagate breadth-first with a witness
  chain per (function, role);
* the **lock model** — attributes/module globals initialized from
  ``threading.Lock/RLock/Condition``, lexical ``with``-lock scopes, the
  set of locks held at every call / attribute-write / blocking-call
  site, plus an *inherited-held* fixpoint (a helper whose every call
  site holds lock L is treated as running under L).

Resolution is deliberately conservative: an ``obj.method()`` whose
receiver cannot be typed is **not** followed (bounds false reach), and
``__init__`` bodies are construction — they happen-before any thread
start and are exempt from role accounting.

The model is heuristic and lexical, like the rest of graftcheck tier 1;
docs/STATIC_ANALYSIS.md ("Concurrency tier") documents the role model
and its escape hatches (``# graftcheck: disable=<pass>`` and the
``# graftcheck: shared=<reason>`` registry).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from gene2vec_tpu.analysis.astpass import (
    ModuleSource,
    chain_of,
    iter_py_files,
    resolve_chain,
)

#: thread roles.  ``main`` is implicit: a function no thread entry
#: reaches runs only on the importing/CLI thread.
ROLE_LOOP = "loop"
ROLE_WORKER = "worker"
ROLE_MONITOR = "monitor"
ROLE_WRITER = "writer"
ROLE_MAIN = "main"

#: the event-loop callback shape (mirrors passes_ast's
#: ``event-loop-blocking`` allowlist, which this tier generalizes)
_CALLBACK_RE = re.compile(r"^_?on_[a-z0-9_]+$")

#: classify a Thread by its ``name=`` literal / target-function name
_ROLE_NAME_RULES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    (ROLE_LOOP, ("eventloop", "event_loop", "acceptor", "reactor")),
    (ROLE_WRITER, ("writer", "write", "flush", "ckpt", "checkpoint")),
    (ROLE_MONITOR, (
        "monitor", "watch", "poll", "respawn", "scrape", "refresh",
        "supervis", "reap", "sweep", "janitor", "timer", "tick",
        "heartbeat", "probe", "canary",
    )),
)

_LOCK_FACTORIES = ("threading.Lock", "threading.RLock", "threading.Condition")

#: blocking calls by resolved dotted chain (the ISSUE-17 set: sleep /
#: fsync / json encode / subprocess; jax dispatch via _BLOCKING_PREFIXES)
_BLOCKING_CHAINS = {
    "time.sleep", "json.dumps", "json.dump", "os.fsync", "os.fdatasync",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call", "subprocess.Popen", "socket.create_connection",
    "open",
}
_BLOCKING_PREFIXES = ("jax.",)  # any jax dispatch blocks the caller
#: blocking by method name on an untyped receiver (socket I/O + device
#: sync).  Deliberately excludes send/sendmsg: the loop's _flush path
#: writes to non-blocking sockets and the noise would drown the signal.
_BLOCKING_ATTRS = {
    "sendall", "recv", "recv_into", "makefile", "accept",
    "block_until_ready",
}

_SHARED_PRAGMA = re.compile(r"#\s*graftcheck:\s*shared=(.+?)\s*$")

#: container-mutating method names counted as writes of the receiver
#: attribute.  queue.put/put_nowait are deliberately absent: a bounded
#: queue IS the sanctioned cross-thread handoff idiom.
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end",
}

FuncKey = str          # "rel::Class.name" / "rel::name" / "rel::<lambda>@L17"
LockId = str           # "rel::Class._lock" / "rel::_cache_lock"
ClassKey = Tuple[str, str]  # (rel, class name)


@dataclasses.dataclass
class CallSite:
    callee: "FuncInfo"
    line: int
    held: FrozenSet[LockId]          # lexically held at the call


@dataclasses.dataclass
class WriteSite:
    attr_id: Tuple[str, Optional[str], str]  # (rel, class|None, attr)
    line: int
    held: FrozenSet[LockId]
    func: "FuncInfo"


@dataclasses.dataclass
class BlockSite:
    desc: str                        # "time.sleep", ".recv", "jax dispatch"
    line: int
    held: FrozenSet[LockId]
    func: "FuncInfo"


@dataclasses.dataclass
class FuncInfo:
    key: FuncKey
    node: ast.AST                    # FunctionDef | AsyncFunctionDef | Lambda
    mod: ModuleSource
    cls: Optional[str]               # enclosing class name
    name: str
    roles: Set[str] = dataclasses.field(default_factory=set)
    #: role -> (reason, caller FuncInfo | None, line) — witness link for
    #: rendering entry -> ... -> here call chains
    role_via: Dict[str, Tuple[str, Optional["FuncInfo"], int]] = (
        dataclasses.field(default_factory=dict)
    )
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    acquires: List[Tuple[LockId, int, FrozenSet[LockId]]] = (
        dataclasses.field(default_factory=list)
    )
    writes: List[WriteSite] = dataclasses.field(default_factory=list)
    blocking: List[BlockSite] = dataclasses.field(default_factory=list)
    #: locks held at EVERY call site of this function (inherited-held
    #: fixpoint); None until computed, frozenset() when nothing common
    inherited: Optional[FrozenSet[LockId]] = None

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclasses.dataclass
class ThreadModel:
    """The whole-package concurrency model passes query."""

    modules: Dict[str, ModuleSource]            # rel -> module
    funcs: Dict[FuncKey, FuncInfo]
    #: (rel, class|None, attr) -> declared justification from the
    #: ``# graftcheck: shared=<reason>`` pragma registry
    shared_declared: Dict[Tuple[str, Optional[str], str], str]
    #: lock id -> roles of every function that acquires it
    lock_roles: Dict[LockId, Set[str]] = dataclasses.field(
        default_factory=dict
    )

    def roles_of(self, fn: FuncInfo) -> Set[str]:
        return fn.roles if fn.roles else {ROLE_MAIN}

    def role_chain(self, fn: FuncInfo, role: str) -> List[str]:
        """Witness path entry -> ... -> fn for one propagated role."""
        hops: List[str] = []
        cur: Optional[FuncInfo] = fn
        guard = 0
        while cur is not None and guard < 32:
            guard += 1
            via = cur.role_via.get(role)
            if via is None:
                hops.append(cur.qual)
                break
            reason, parent, line = via
            if parent is None:
                hops.append(f"{cur.qual} [{reason}]")
                break
            hops.append(f"{cur.qual} (called at {parent.mod.rel}:{line})")
            cur = parent
        return list(reversed(hops))


def _classify_thread_name(text: str) -> str:
    low = text.lower()
    for role, needles in _ROLE_NAME_RULES:
        if any(n in low for n in needles):
            return role
    return ROLE_WORKER


def _str_fragments(node: Optional[ast.AST]) -> str:
    """Literal text of a str constant or the literal parts of an
    f-string (``f"{name}-{i}"`` -> "-")."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(
            v.value for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        )
    return ""


def _iter_own(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested function
    definitions (they are separate FuncInfos with their own sites)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(child))


def _module_rel_of(dotted: str, modules: Dict[str, ModuleSource]) -> Optional[str]:
    """"gene2vec_tpu.serve.eventloop" -> its rel path, if loaded."""
    rel = dotted.replace(".", os.sep) + ".py"
    if rel in modules:
        return rel
    rel_init = dotted.replace(".", os.sep) + os.sep + "__init__.py"
    return rel_init if rel_init in modules else None


class _ModuleIndex:
    """Per-module symbol tables the resolver needs."""

    def __init__(self, mod: ModuleSource):
        self.mod = mod
        self.toplevel: Dict[str, ast.AST] = {}
        self.classes: Dict[str, Dict[str, ast.AST]] = {}   # cls -> methods
        self.class_of_method: Dict[str, List[str]] = {}    # method -> classes
        self.module_locks: Set[str] = set()
        #: (cls, attr) -> ClassKey of the instance stored there
        self.attr_types: Dict[Tuple[str, str], ClassKey] = {}
        #: (cls, attr) -> element ClassKey for list-of-instances attrs
        self.attr_elem_types: Dict[Tuple[str, str], ClassKey] = {}
        self.lock_attrs: Dict[Tuple[str, str], int] = {}   # (cls, attr) -> line
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.toplevel[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        methods[item.name] = item
                        self.class_of_method.setdefault(
                            item.name, []
                        ).append(node.name)
                self.classes[node.name] = methods
            elif isinstance(node, ast.Assign):
                if (
                    isinstance(node.value, ast.Call)
                    and self._is_lock_factory(node.value)
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_locks.add(tgt.id)

    def _is_lock_factory(self, call: ast.Call) -> bool:
        chain = chain_of(call.func)
        if chain is None:
            return False
        return resolve_chain(chain, self.mod.imports()) in _LOCK_FACTORIES


def build_model(
    repo_root: str,
    files: Optional[List[str]] = None,
    package_dir: str = "gene2vec_tpu",
) -> ThreadModel:
    """Parse the package (or an explicit file list) and derive the
    role/lock model.  Pure and jax-free; ~100ms for the full package."""
    modules: Dict[str, ModuleSource] = {}
    if files is not None:
        paths = [os.path.abspath(f) for f in files]
    else:
        paths = list(iter_py_files(os.path.join(repo_root, package_dir)))
    for path in paths:
        mod = ModuleSource.load(path, repo_root)
        if mod is not None:
            modules[mod.rel] = mod

    indexes = {rel: _ModuleIndex(m) for rel, m in modules.items()}
    model = ThreadModel(modules=modules, funcs={}, shared_declared={})

    # ---- function inventory (incl. nested defs and lambdas) --------------
    func_of_node: Dict[int, FuncInfo] = {}
    class_stack_of: Dict[int, Optional[str]] = {}

    for rel, mod in modules.items():
        def visit(parent: ast.AST, cls: Optional[str], fn_depth: int) -> None:
            for child in ast.iter_child_nodes(parent):
                if isinstance(child, ast.ClassDef):
                    # only top-level classes own methods for role keys;
                    # nested classes keep the outer name for display
                    visit(child, child.name if fn_depth == 0 else cls, fn_depth)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    name = getattr(child, "name", f"<lambda>@{child.lineno}")
                    in_class = cls if fn_depth == 0 else None
                    qual = f"{in_class}.{name}" if in_class else name
                    key = f"{rel}::{qual}"
                    if key in model.funcs:          # same-named siblings
                        key = f"{rel}::{qual}@{child.lineno}"
                    fi = FuncInfo(key, child, mod, in_class or cls, name)
                    model.funcs[key] = fi
                    func_of_node[id(child)] = fi
                    class_stack_of[id(child)] = cls
                    visit(child, cls, fn_depth + 1)
                else:
                    visit(child, cls, fn_depth)

        visit(mod.tree, None, 0)

    # ---- attribute types + lock attrs (from any method body) -------------
    def resolve_class(chain: str, mod: ModuleSource) -> Optional[ClassKey]:
        resolved = resolve_chain(chain, mod.imports())
        idx = indexes[mod.rel]
        if resolved in idx.classes:
            return (mod.rel, resolved)
        head, _, cls_name = resolved.rpartition(".")
        target_rel = _module_rel_of(head, modules) if head else None
        if target_rel and cls_name in indexes[target_rel].classes:
            return (target_rel, cls_name)
        return None

    for rel, mod in modules.items():
        idx = indexes[rel]
        for fi in (f for f in model.funcs.values() if f.mod is mod and f.cls):
            for node in _iter_own(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    val = node.value
                    if isinstance(val, ast.Call):
                        if idx._is_lock_factory(val):
                            idx.lock_attrs[(fi.cls, tgt.attr)] = node.lineno
                            continue
                        chain = chain_of(val.func)
                        ck = resolve_class(chain, mod) if chain else None
                        if ck is not None:
                            idx.attr_types[(fi.cls, tgt.attr)] = ck
                    elif isinstance(val, ast.Name):
                        # ``self.app = app`` where the enclosing method
                        # annotates ``app: ServeApp`` — param typing
                        ann = _param_annotation(fi.node, val.id)
                        chain = chain_of(ann) if ann is not None else None
                        ck = resolve_class(chain, mod) if chain else None
                        if ck is not None:
                            idx.attr_types[(fi.cls, tgt.attr)] = ck
                    elif isinstance(val, ast.ListComp) and isinstance(
                        val.elt, ast.Call
                    ):
                        chain = chain_of(val.elt.func)
                        ck = resolve_class(chain, mod) if chain else None
                        if ck is not None:
                            idx.attr_elem_types[(fi.cls, tgt.attr)] = ck

    # ---- shared= pragma registry -----------------------------------------
    for rel, mod in modules.items():
        for lineno, text in enumerate(mod.lines, start=1):
            m = _SHARED_PRAGMA.search(text)
            if not m:
                continue
            # the pragma anchors a `self.attr = ...` (or `global`-write)
            # line; register the attr it declares
            code = text.split("#", 1)[0]
            owner = _owning_class_at(mod, lineno, model)
            registered = False
            try:
                stmt = ast.parse(code.strip()).body
            except SyntaxError:
                stmt = []
            for node in stmt[:1]:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        model.shared_declared[(rel, owner, tgt.attr)] = (
                            m.group(1)
                        )
                        registered = True
                    elif isinstance(tgt, ast.Name):
                        model.shared_declared[(rel, None, tgt.id)] = m.group(1)
                        registered = True
            if not registered and "=" in code:
                # multi-line statement head (`self.x: T = (` won't parse
                # alone): fall back to a lexical target match
                m2 = re.match(r"\s*self\.(\w+)\b", code)
                if m2:
                    model.shared_declared[(rel, owner, m2.group(1))] = (
                        m.group(1)
                    )

    # ---- call edges + lock scopes + write/blocking sites -----------------
    for fi in model.funcs.values():
        _scan_function(fi, indexes, modules, model, func_of_node)

    # ---- thread entry discovery ------------------------------------------
    _discover_entries(model, indexes, modules, func_of_node)

    # ---- role propagation (BFS over call edges) --------------------------
    frontier = [f for f in model.funcs.values() if f.roles]
    while frontier:
        nxt: List[FuncInfo] = []
        for f in frontier:
            for site in f.calls:
                g = site.callee
                if g.name == "__init__":
                    continue  # construction happens-before thread start
                new = f.roles - g.roles
                if new:
                    g.roles |= new
                    for role in new:
                        g.role_via.setdefault(role, ("call", f, site.line))
                    nxt.append(g)
        frontier = nxt

    # ---- inherited-held fixpoint -----------------------------------------
    callers: Dict[FuncKey, List[Tuple[FuncInfo, CallSite]]] = {}
    for f in model.funcs.values():
        for site in f.calls:
            callers.setdefault(site.callee.key, []).append((f, site))
    entry_funcs = {
        f.key for f in model.funcs.values()
        if any(parent is None for _, parent, _ in f.role_via.values())
    }
    for _ in range(12):
        changed = False
        for g in model.funcs.values():
            sites = callers.get(g.key)
            if not sites or g.key in entry_funcs:
                continue  # entries run with no caller-held locks
            acc: Optional[FrozenSet[LockId]] = None
            for caller, site in sites:
                held = site.held | (caller.inherited or frozenset())
                acc = held if acc is None else (acc & held)
            acc = acc or frozenset()
            if acc != g.inherited:
                g.inherited = acc
                changed = True
        if not changed:
            break
    # an entry point / uncalled function inherits nothing
    for g in model.funcs.values():
        if g.inherited is None:
            g.inherited = frozenset()

    # ---- lock -> acquirer roles ------------------------------------------
    for f in model.funcs.values():
        for lock_id, _line, _held in f.acquires:
            model.lock_roles.setdefault(lock_id, set()).update(
                model.roles_of(f)
            )
    return model


def _param_annotation(fn_node: ast.AST, name: str) -> Optional[ast.AST]:
    """The annotation expression of parameter ``name``, if any."""
    args = getattr(fn_node, "args", None)
    if args is None:
        return None
    for a in list(args.args) + list(args.kwonlyargs) + list(args.posonlyargs):
        if a.arg == name:
            return a.annotation
    return None


def _owning_class_at(
    mod: ModuleSource, lineno: int, model: ThreadModel
) -> Optional[str]:
    """The class whose method spans ``lineno`` (for pragma anchoring)."""
    best: Optional[FuncInfo] = None
    for f in model.funcs.values():
        if f.mod is not mod or f.cls is None:
            continue
        end = getattr(f.node, "end_lineno", f.node.lineno)
        if f.node.lineno <= lineno <= end:
            if best is None or f.node.lineno > best.node.lineno:
                best = f
    return best.cls if best else None


def _scan_function(
    fi: FuncInfo,
    indexes: Dict[str, _ModuleIndex],
    modules: Dict[str, ModuleSource],
    model: ThreadModel,
    func_of_node: Dict[int, FuncInfo],
) -> None:
    mod = fi.mod
    idx = indexes[mod.rel]
    imports = mod.imports()
    local_types: Dict[str, ClassKey] = {}
    # seed locals from parameter annotations (`def f(app: ServeApp)`)
    args = getattr(fi.node, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is None:
                continue
            chain = chain_of(a.annotation)
            if chain is not None:
                ck = _resolve_class_key(chain, mod, indexes, modules)
                if ck is not None:
                    local_types[a.arg] = ck

    def lock_id_of(expr: ast.AST) -> Optional[LockId]:
        chain = chain_of(expr)
        if chain is None:
            return None
        if chain.startswith("self.") and fi.cls:
            attr = chain[5:]
            if (fi.cls, attr) in idx.lock_attrs:
                return f"{mod.rel}::{fi.cls}.{attr}"
            return None
        if "." not in chain and chain in idx.module_locks:
            return f"{mod.rel}::{chain}"
        return None

    def class_of_receiver(parts: List[str]) -> Optional[ClassKey]:
        """Type a dotted receiver: ``self[.attr]*`` / ``var[.attr]*``,
        folding each hop through the owning module's attr_types."""
        if not parts:
            return None
        if parts[0] == "self":
            if not fi.cls:
                return None
            cur: Optional[ClassKey] = (mod.rel, fi.cls)
        elif parts[0] in local_types:
            cur = local_types[parts[0]]
        else:
            return None
        for attr in parts[1:]:
            cur = indexes[cur[0]].attr_types.get((cur[1], attr))
            if cur is None:
                return None
        return cur

    def resolve_callee(call: ast.Call) -> Optional[FuncInfo]:
        chain = chain_of(call.func)
        if chain is None:
            return None
        parts = chain.split(".")
        # bare name: lexical nested def, then module top level, then
        # `from package.mod import fn` imports
        if len(parts) == 1:
            name = parts[0]
            hit = _resolve_bare(name, fi, idx, func_of_node)
            if hit is not None:
                return hit
            resolved = imports.get(name)
            if resolved and resolved.startswith("gene2vec_tpu."):
                head, _, fn_name = resolved.rpartition(".")
                target_rel = _module_rel_of(head, modules)
                if target_rel:
                    node = indexes[target_rel].toplevel.get(fn_name)
                    return (
                        func_of_node.get(id(node)) if node is not None else None
                    )
            return None
        # typed receiver: self.m() / self.attr.m() / var.m() /
        # var.attr.m() / self.a.b.m() ... through attr_types hops
        ck = class_of_receiver(parts[:-1])
        if ck is not None:
            node = indexes[ck[0]].classes.get(ck[1], {}).get(parts[-1])
            return func_of_node.get(id(node)) if node is not None else None
        # alias.fn() through a package-module import
        if len(parts) == 2:
            base = imports.get(parts[0], parts[0])
            target_rel = _module_rel_of(base, modules)
            if target_rel:
                node = indexes[target_rel].toplevel.get(parts[1])
                return func_of_node.get(id(node)) if node is not None else None
        return None

    def visit(node: ast.AST, held: FrozenSet[LockId]) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # nested defs scanned as their own FuncInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                lid = lock_id_of(item.context_expr)
                if lid is not None:
                    fi.acquires.append((lid, node.lineno, inner))
                    inner = inner | {lid}
                visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            # local instance typing: x = ClassName(...) / x = self.attr
            if len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                ck = None
                if isinstance(node.value, ast.Call):
                    chain = chain_of(node.value.func)
                    if chain is not None:
                        ck = _resolve_class_key(chain, mod, indexes, modules)
                elif isinstance(node.value, ast.Attribute):
                    chain = chain_of(node.value)
                    if chain is not None:
                        parts = chain.split(".")
                        ck = class_of_receiver(parts[:-1])
                        if ck is not None:
                            ck = indexes[ck[0]].attr_types.get(
                                (ck[1], parts[-1])
                            )
                if ck is not None:
                    local_types[node.targets[0].id] = ck
            _record_write_targets(node.targets, node.lineno, held)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if not (isinstance(node, ast.AnnAssign) and node.value is None):
                _record_write_targets([node.target], node.lineno, held)
        if isinstance(node, ast.Call):
            callee = resolve_callee(node)
            if callee is not None:
                fi.calls.append(CallSite(callee, node.lineno, held))
            _record_blocking(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    def _record_write_targets(
        targets: List[ast.AST], lineno: int, held: FrozenSet[LockId]
    ) -> None:
        if fi.name == "__init__":
            return  # construction happens-before thread start
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                _record_write_targets(list(tgt.elts), lineno, held)
                continue
            if isinstance(tgt, ast.Subscript):
                # self.x[k] = v mutates the container self.x holds
                _record_write_targets([tgt.value], lineno, held)
                continue
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and fi.cls
            ):
                fi.writes.append(WriteSite(
                    (mod.rel, fi.cls, tgt.attr), lineno, held, fi
                ))
            elif isinstance(tgt, ast.Name) and tgt.id in _globals_of(fi):
                fi.writes.append(WriteSite(
                    (mod.rel, None, tgt.id), lineno, held, fi
                ))

    def _record_blocking(call: ast.Call, held: FrozenSet[LockId]) -> None:
        chain = chain_of(call.func)
        if chain is None:
            return
        # self.x.append(...) / cache.update(...): a container mutation
        # is a write of the receiver attribute for lock discipline
        parts = chain.split(".")
        if parts[-1] in _MUTATOR_METHODS and len(parts) >= 2:
            if parts[0] == "self" and len(parts) == 3 and fi.cls:
                _record_write_targets(
                    [ast.Attribute(
                        value=ast.Name(id="self", ctx=ast.Load()),
                        attr=parts[1], ctx=ast.Store(),
                    )],
                    call.lineno, held,
                )
            elif len(parts) == 2 and parts[0] in _globals_of(fi):
                fi.writes.append(WriteSite(
                    (mod.rel, None, parts[0]), call.lineno, held, fi
                ))
        resolved = resolve_chain(chain, imports)
        if resolved in _BLOCKING_CHAINS:
            fi.blocking.append(BlockSite(resolved, call.lineno, held, fi))
            return
        if any(resolved.startswith(p) for p in _BLOCKING_PREFIXES):
            fi.blocking.append(
                BlockSite(f"jax dispatch ({resolved})", call.lineno, held, fi)
            )
            return
        attr = chain.rsplit(".", 1)[-1]
        if "." in chain and attr in _BLOCKING_ATTRS:
            fi.blocking.append(BlockSite(f".{attr}", call.lineno, held, fi))

    for top in ast.iter_child_nodes(fi.node):
        visit(top, frozenset())


def _globals_of(fi: FuncInfo) -> Set[str]:
    names: Set[str] = set()
    for node in _iter_own(fi.node):
        if isinstance(node, ast.Global):
            names.update(node.names)
    return names


def _resolve_bare(
    name: str, fi: FuncInfo, idx: _ModuleIndex,
    func_of_node: Dict[int, FuncInfo],
) -> Optional[FuncInfo]:
    """A bare callee name: nested def in this function, else module top
    level (a sibling method is never assumed — that needs ``self.``)."""
    for node in _iter_own(fi.node):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == name
        ):
            return func_of_node.get(id(node))
    node = idx.toplevel.get(name)
    return func_of_node.get(id(node)) if node is not None else None


def _resolve_class_key(
    chain: str, mod: ModuleSource,
    indexes: Dict[str, _ModuleIndex],
    modules: Dict[str, ModuleSource],
) -> Optional[ClassKey]:
    resolved = resolve_chain(chain, mod.imports())
    if resolved in indexes[mod.rel].classes:
        return (mod.rel, resolved)
    head, _, cls_name = resolved.rpartition(".")
    target_rel = _module_rel_of(head, modules) if head else None
    if target_rel and cls_name in indexes[target_rel].classes:
        return (target_rel, cls_name)
    return None


def _discover_entries(
    model: ThreadModel,
    indexes: Dict[str, _ModuleIndex],
    modules: Dict[str, ModuleSource],
    func_of_node: Dict[int, FuncInfo],
) -> None:
    """Tag thread entry points with their roles + entry reasons."""
    # (1) event-loop callbacks: _on_* methods.  Scoped to serve/ (the
    # event-loop plane — same jurisdiction passes_ast's
    # event-loop-blocking has): obs/resilience reuse the on_* naming for
    # alert/signal callbacks that run on monitor or main threads.
    for fi in model.funcs.values():
        if (
            fi.cls and _CALLBACK_RE.match(fi.name)
            and f"serve{os.sep}" in fi.mod.rel
        ):
            _tag(fi, ROLE_LOOP, "event-loop callback (_on_*)")

    for fi in list(model.funcs.values()):
        idx = indexes[fi.mod.rel]
        imports = fi.mod.imports()
        # local instance typing for handler registration: the
        # `adapter = ServeAdapter(app); EventLoopHTTPServer(adapter)`
        # idiom needs the var's class to find its __call__
        local_types: Dict[str, ClassKey] = {}
        for node in _iter_own(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                chain = chain_of(node.value.func)
                ck = (
                    _resolve_class_key(chain, fi.mod, indexes, modules)
                    if chain else None
                )
                if ck is not None:
                    local_types[node.targets[0].id] = ck
        for node in _iter_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            chain = chain_of(node.func)
            resolved = resolve_chain(chain, imports) if chain else None
            # (2) threading.Thread(target=..., name=...)
            if resolved == "threading.Thread":
                target = _kwarg(node, "target")
                name_txt = _str_fragments(_kwarg(node, "name"))
                cb = _callback_func(
                    target, fi, idx, indexes, modules, func_of_node
                )
                if cb is not None:
                    role = _classify_thread_name(name_txt or cb.name)
                    _tag(cb, role, f"Thread target at {fi.mod.rel}:{node.lineno}")
                continue
            # (2b) event-loop server construction: the ``handler`` /
            # ``on_*`` callables handed to a serve/-plane class
            # constructor are invoked on the loop thread
            ctor = (
                _resolve_class_key(chain, fi.mod, indexes, modules)
                if chain else None
            )
            if ctor is not None and f"serve{os.sep}" in ctor[0]:
                init = indexes[ctor[0]].classes.get(ctor[1], {}).get("__init__")
                if init is not None:
                    params = [a.arg for a in init.args.args[1:]]
                    bound: List[Tuple[str, ast.AST]] = list(
                        zip(params, node.args)
                    )
                    bound.extend(
                        (kw.arg, kw.value)
                        for kw in node.keywords if kw.arg
                    )
                    for pname, arg in bound:
                        if pname != "handler" and not _CALLBACK_RE.match(pname):
                            continue
                        cb = None
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in local_types
                        ):
                            ck2 = local_types[arg.id]
                            mnode = indexes[ck2[0]].classes.get(
                                ck2[1], {}
                            ).get("__call__")
                            cb = (
                                func_of_node.get(id(mnode))
                                if mnode is not None else None
                            )
                        if cb is None:
                            cb = _callback_func(
                                arg, fi, idx, indexes, modules, func_of_node
                            )
                        if cb is not None:
                            _tag(
                                cb, ROLE_LOOP,
                                f"event-loop handler registered at "
                                f"{fi.mod.rel}:{node.lineno}",
                            )
                continue
            # (3) pool.submit(fn) / submit_async(..., on_done=fn) /
            #     observers.append(fn) / add_observer(fn)
            attr = chain.rsplit(".", 1)[-1] if chain and "." in chain else None
            cb_args: List[Tuple[ast.AST, str]] = []
            if attr in ("submit", "submit_async"):
                receiver = chain.rsplit(".", 1)[0]
                role = (
                    ROLE_WRITER if "writer" in receiver.lower()
                    or "ckpt" in receiver.lower() else ROLE_WORKER
                )
                for a in node.args[:1]:
                    cb_args.append((a, role))
                od = _kwarg(node, "on_done")
                if od is not None:
                    cb_args.append((od, ROLE_WORKER))
            elif attr in ("add_observer", "register_observer"):
                for a in node.args[:1]:
                    cb_args.append((a, ROLE_WORKER))
            elif attr == "append" and chain.endswith("observers.append"):
                for a in node.args[:1]:
                    cb_args.append((a, ROLE_WORKER))
            else:
                od = _kwarg(node, "on_done")
                if od is not None:
                    cb_args.append((od, ROLE_WORKER))
            for arg, role in cb_args:
                cb = _callback_func(
                    arg, fi, idx, indexes, modules, func_of_node
                )
                if cb is not None:
                    _tag(
                        cb, role,
                        f"callback registered at {fi.mod.rel}:{node.lineno}",
                    )


def _tag(fi: FuncInfo, role: str, reason: str) -> None:
    if role not in fi.roles:
        fi.roles.add(role)
        fi.role_via.setdefault(role, (reason, None, fi.node.lineno))


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _callback_func(
    expr: Optional[ast.AST],
    fi: FuncInfo,
    idx: _ModuleIndex,
    indexes: Dict[str, _ModuleIndex],
    modules: Dict[str, ModuleSource],
    func_of_node: Dict[int, FuncInfo],
) -> Optional[FuncInfo]:
    """Resolve a function-valued expression to its FuncInfo: a lambda,
    ``self.method``, a bare def name, or ``obj.method`` through local /
    attribute / unique-in-module typing."""
    if expr is None:
        return None
    if isinstance(expr, ast.Lambda):
        return func_of_node.get(id(expr))
    chain = chain_of(expr)
    if chain is None:
        return None
    parts = chain.split(".")
    if parts[0] == "self" and fi.cls:
        if len(parts) == 2:
            node = idx.classes.get(fi.cls, {}).get(parts[1])
            return func_of_node.get(id(node)) if node is not None else None
        if len(parts) == 3:
            ck = idx.attr_types.get((fi.cls, parts[1]))
            if ck is None:
                ck = idx.attr_elem_types.get((fi.cls, parts[1]))
            if ck is not None:
                node = indexes[ck[0]].classes.get(ck[1], {}).get(parts[2])
                return func_of_node.get(id(node)) if node is not None else None
        return None
    if len(parts) == 1:
        return _resolve_bare(parts[0], fi, idx, func_of_node)
    if len(parts) == 2:
        method = parts[1]
        # last resort: a method name defined by exactly ONE class in
        # this module (covers `Thread(target=loop.run)` where `loop`
        # iterates a typed list attribute), else by exactly one class
        # package-wide (`Thread(target=server.serve_forever)`) —
        # common names (run, submit, get, ...) stay ambiguous and are
        # conservatively not followed
        owners = idx.class_of_method.get(method, [])
        if len(owners) == 1:
            node = idx.classes[owners[0]].get(method)
            return func_of_node.get(id(node)) if node is not None else None
        hits = [
            other.classes[c][method]
            for other in indexes.values()
            for c in other.class_of_method.get(method, [])
        ]
        if len(hits) == 1:
            return func_of_node.get(id(hits[0]))
    return None
