"""Perf-plane budget gates: timeline overhead + bench-trajectory
regressions vs budgets.json ``perf``.

Two checks, both jax-free and I/O-only so they ride the DEFAULT
``cli.analyze`` tier (the passes_fleet / passes_obs shape):

1. **Timeline overhead** — ``python bench.py --timeline-overhead``
   measures timeline-on vs timeline-off SGNS throughput at the recipe
   pinned in ``perf.timeline_overhead`` and stamps
   ``BENCH_PERF_r10.json``; this pass re-checks the committed record.
   A missing bench is an *info* finding (a fresh checkout must not
   fail lint before its first bench); a record that exists and
   violates — or omits — a budgeted quantity, or was measured with a
   different recipe, gates hard (the passes_obs recipe-pinning
   lesson: a lucky tiny window must not pass a 2% gate).

2. **Trajectory regressions** — the unified bench ledger
   (:mod:`gene2vec_tpu.obs.ledger`) ingests every root bench artifact
   and ``perf.regression`` rules compare each configured metric's
   newest point against the median of its trailing window.  A
   detected regression is an error finding; short series and clean
   series are informational.

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (the planted-
regression fixtures and CI sandboxes point it at a staged directory).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

PERF_ROOT_ENV = "GENE2VEC_TPU_PERF_ROOT"
BENCH_PERF_NAME = "BENCH_PERF_r10.json"

_PASS_OVERHEAD = "perf-timeline-overhead-budget"
_PASS_REGRESSION = "perf-ledger-regression"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def perf_root() -> str:
    return os.environ.get(PERF_ROOT_ENV) or REPO_ROOT


def perf_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """All perf-plane findings: overhead gate + trajectory regressions."""
    budgets: Dict = load_budgets(budgets_path).get("perf", {})
    if not budgets:
        return []
    root = root or perf_root()
    findings: List[Finding] = []
    overhead_budget = budgets.get("timeline_overhead")
    if isinstance(overhead_budget, dict):
        findings.extend(_overhead_findings(root, overhead_budget))
    regression_rules = budgets.get("regression")
    if isinstance(regression_rules, dict):
        findings.extend(_regression_findings(root, regression_rules))
    return findings


# -- timeline overhead -------------------------------------------------------


def _newest_perf_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_PERF_r*`` artifact under ``root`` (highest
    round wins, mtime breaks ties) — the gate must follow the round
    convention like the ledger does, not pin one filename forever."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        if ledger.match_family(name) and name.startswith("BENCH_PERF"):
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime, path))
    if not candidates:
        return None
    return max(candidates)[2]


def _overhead_findings(root: str, budget: Dict) -> List[Finding]:
    path = _newest_perf_bench(root) or os.path.join(root, BENCH_PERF_NAME)
    label = os.path.basename(path)
    if not os.path.exists(path):
        return [Finding(
            pass_id=_PASS_OVERHEAD,
            severity="info",
            path=label,
            message=(
                f"no timeline-overhead bench recorded yet ({label} "
                "missing); run `python bench.py --timeline-overhead` "
                "(it reads the pinned recipe from budgets.json 'perf') "
                "to stamp one"
            ),
        )]
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS_OVERHEAD,
            path=label,
            message=f"unreadable timeline-overhead bench: {e}",
        )]

    ceiling = float(budget["max_overhead_fraction"])
    regression = _get(bench, "regression_frac")
    recipe = bench.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    data: Dict = {
        "regression_frac": regression,
        "max_overhead_fraction": ceiling,
        "recipe": recipe,
    }
    problems: List[str] = []
    # every budgeted quantity must be PRESENT — dropping the key must
    # gate like a violation (the passes_fleet lesson)
    for key, value in (
        ("regression_frac", regression),
        ("rate_timeline_on", _get(bench, "rate_timeline_on")),
        ("rate_timeline_off", _get(bench, "rate_timeline_off")),
    ):
        if value is None:
            problems.append(f"{key} missing from the bench record")
    # the budget pins the MEASUREMENT RECIPE: geometry, rounds AND
    # window length must match, or a lucky tiny window passes the 2%
    # gate by variance
    for key in ("dim", "vocab", "num_pairs", "batch_pairs", "rounds",
                "epochs_per_window"):
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(recipe, key)
        data[f"budget_{key}"] = pinned
        if measured is None:
            problems.append(f"recipe.{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"bench measured with {key}={measured:g} but the budget "
                f"pins {key}={pinned:g} — re-run `python bench.py "
                "--timeline-overhead`"
            )
    if regression is not None and regression > ceiling:
        problems.append(
            f"timeline-on vs timeline-off throughput regression "
            f"{regression:.4f} > budget {ceiling} (step-phase "
            "instrumentation grew past its ceiling)"
        )
    if problems:
        return [Finding(
            pass_id=_PASS_OVERHEAD,
            path=label,
            message=(
                "timeline-overhead record violates the perf budget: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS_OVERHEAD,
        severity="info",
        path=label,
        message=(
            f"timeline-on vs timeline-off throughput regression "
            f"{regression:+.4f} within budget (<= {ceiling})"
        ),
        data=data,
    )]


# -- ledger trajectory regressions -------------------------------------------


def _regression_findings(root: str, rules: Dict) -> List[Finding]:
    from gene2vec_tpu.obs import ledger

    records = ledger.ingest_root(root)
    findings: List[Finding] = []
    if not records:
        return [Finding(
            pass_id=_PASS_REGRESSION,
            severity="info",
            path=os.path.basename(root) or root,
            message=(
                f"no bench artifacts found under {root}; the trajectory "
                "gate has nothing to check (run the benches in "
                "docs/BENCHMARKS.md to populate it)"
            ),
        )]
    broken = [r for r in records if r.get("error")]
    for rec in broken:
        # an unreadable artifact silently drops its series point — the
        # exact blind spot this gate exists to prevent
        findings.append(Finding(
            pass_id=_PASS_REGRESSION,
            path=rec["source"],
            message=f"bench artifact failed to ingest: {rec['error']}",
        ))
    for ev in ledger.detect_regressions(records, rules):
        label = ev.get("newest_source") or ev["metric"]
        if ev.get("skipped"):
            findings.append(Finding(
                pass_id=_PASS_REGRESSION,
                severity="info",
                path=ev["metric"],
                message=(
                    f"trajectory gate for {ev['metric']!r} skipped: "
                    f"{ev['skipped']}"
                ),
                data=ev,
            ))
        elif ev["regressed"]:
            findings.append(Finding(
                pass_id=_PASS_REGRESSION,
                path=label,
                message=(
                    f"bench trajectory REGRESSION in {ev['metric']!r}: "
                    f"newest {ev['newest_value']:g} vs trailing-window "
                    f"median {ev['band_median']:g} is "
                    f"{ev['regression_frac']:.2%} worse (max "
                    f"{ev['max_regression_frac']:g}); if intentional, "
                    "re-baseline per docs/BENCHMARKS.md"
                ),
                data=ev,
            ))
        else:
            findings.append(Finding(
                pass_id=_PASS_REGRESSION,
                severity="info",
                path=label,
                message=(
                    f"{ev['metric']}: newest {ev.get('newest_value')} vs "
                    f"band median {ev.get('band_median')} within "
                    f"max_regression_frac {ev['max_regression_frac']:g}"
                ),
                data=ev,
            ))
    return findings
