"""AST pass framework: module loading, import resolution, traced scopes.

The JAX-footgun passes all need the same two facts about a module:

* which dotted names mean what (``np`` → ``numpy``, ``lax`` →
  ``jax.lax``) — :func:`import_table` + :func:`resolve_chain`;
* which function bodies execute **under a trace** (inside ``jit`` /
  ``scan`` / ``vmap`` / ... ) — :func:`traced_functions`.

Trace detection is lexical and name-based, deliberately: a function is
traced when it is (a) decorated with a jit-like wrapper, (b) passed by
name in a *function-valued argument position* of a trace-entry call
anywhere in the module (``jax.jit(f)``, ``lax.scan(body, ...)`` — see
:data:`TRACE_HOF_FN_ARGS`; carry/operand positions never mark), or (c)
lexically nested inside a traced function.  Helpers *called* from traced code are not followed — that is
an inter-procedural analysis this tier does not attempt (documented in
docs/STATIC_ANALYSIS.md), and in practice the repo's traced helpers are
nested closures, which (c) covers.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: wrappers whose *argument function* runs traced
TRACE_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.pmap", "jax.vmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat", "jax.linearize",
    "jax.experimental.shard_map.shard_map", "jax.jacfwd", "jax.jacrev",
}

#: higher-order control-flow entry points: first (or any) function-valued
#: argument runs traced
TRACE_HOF = {
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.map", "jax.lax.cond", "jax.lax.switch",
    "jax.lax.associative_scan", "jax.lax.custom_root",
}

#: which positional indices of each HOF are function-valued — only those
#: mark a passed name as traced.  Without this, a carry/xs operand whose
#: local name collides with a module-level function (``lax.scan(body,
#: init, xs)`` where ``init`` is a float carry AND ``def init`` exists
#: host-side) would falsely mark the host function traced.
#: Signatures: scan(f, init, xs) / fori_loop(lo, hi, body, init) /
#: while_loop(cond, body, init) / map(f, xs) / cond(pred, true, false,
#: *ops) / switch(index, branches, *ops) / associative_scan(fn, elems) /
#: custom_root(f, x0, solve, tangent_solve)
TRACE_HOF_FN_ARGS = {
    "jax.lax.scan": (0,),
    "jax.lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.map": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.associative_scan": (0,),
    "jax.lax.custom_root": (0, 2, 3),
}


@dataclasses.dataclass
class ModuleSource:
    """One parsed module, path-relative to the repo root."""

    path: str           # absolute
    rel: str            # repo-relative, for findings
    source: str
    tree: ast.AST
    lines: List[str]

    @classmethod
    def load(cls, path: str, root: str) -> Optional["ModuleSource"]:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(path, root)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None  # the syntax-error finding is the runner's job
        return cls(path, rel, source, tree, source.splitlines())

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def imports(self) -> Dict[str, str]:
        """Memoized :func:`import_table` — every pass needs it, so derive
        it once per module instead of once per pass."""
        cached = getattr(self, "_imports_cache", None)
        if cached is None:
            cached = import_table(self.tree)
            self._imports_cache = cached
        return cached


def import_table(tree: ast.AST) -> Dict[str, str]:
    """Local alias → canonical dotted module path.

    ``import numpy as np`` → {"np": "numpy"};
    ``from jax import numpy as jnp`` → {"jnp": "jax.numpy"};
    ``from numpy import random`` → {"random": "numpy.random"} (shadows a
    bare ``import random`` seen earlier, matching runtime semantics).
    """
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                table[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                table[a.asname or a.name] = f"{node.module}.{a.name}"
    return table


def chain_of(node: ast.AST) -> Optional[str]:
    """``ast.Attribute``/``ast.Name`` → dotted string ("np.random.rand"),
    or None for non-name roots (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve_chain(chain: str, imports: Dict[str, str]) -> str:
    """Rewrite a dotted chain's root through the module's import table:
    ``np.random.rand`` → ``numpy.random.rand``."""
    root, _, rest = chain.partition(".")
    base = imports.get(root, root)
    return f"{base}.{rest}" if rest else base


def _resolved_call_target(call: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    chain = chain_of(call.func)
    return resolve_chain(chain, imports) if chain else None


def is_jit_chain(resolved: Optional[str]) -> bool:
    """Does this resolved dotted name denote ``jax.jit`` (or pjit)?"""
    return resolved in ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


def _trace_entry_kind(resolved: Optional[str]) -> Optional[str]:
    if resolved is None:
        return None
    if resolved in TRACE_WRAPPERS:
        return "wrapper"
    if resolved in TRACE_HOF:
        return "hof"
    # functools.partial(jax.jit, ...) handled at the decorator site
    return None


def _decorator_is_tracing(dec: ast.AST, imports: Dict[str, str]) -> bool:
    """@jax.jit / @jit / @functools.partial(jax.jit, ...) /
    @jax.jit-with-kwargs-call forms."""
    if isinstance(dec, ast.Call):
        resolved = _resolved_call_target(dec, imports)
        if resolved in ("functools.partial", "partial") and dec.args:
            inner = chain_of(dec.args[0])
            if inner and _trace_entry_kind(resolve_chain(inner, imports)):
                return True
        return _trace_entry_kind(resolved) == "wrapper"
    chain = chain_of(dec)
    if chain is None:
        return False
    return _trace_entry_kind(resolve_chain(chain, imports)) == "wrapper"


@dataclasses.dataclass
class TracedFunction:
    node: ast.AST          # FunctionDef | AsyncFunctionDef | Lambda
    reason: str            # "decorator" | "wrapped:<entry>" | "nested:<outer>"
    name: str
    #: the TracedFunction of the nearest *traced* enclosing function, by
    #: node identity (never by name — two traced fns may share a name);
    #: None for roots and for fns nested in untraced factories
    outer: Optional["TracedFunction"] = None


def traced_functions(mod: ModuleSource) -> List[TracedFunction]:
    """Every function definition in the module whose body runs traced.
    Memoized on the module (three passes share the result)."""
    cached = getattr(mod, "_traced_cache", None)
    if cached is None:
        cached = _compute_traced(mod)
        mod._traced_cache = cached
    return cached


def _scope_index(tree: ast.AST):
    """(scope_chain, defs_in): for every node the tuple of enclosing
    function nodes (innermost last), and for every scope (module = None)
    the name → def-node table it defines.  This is what lets a bare-name
    reference at a call site resolve to THE def visible there, instead of
    any same-named def anywhere in the module."""
    scope_chain: Dict[int, Tuple[ast.AST, ...]] = {}
    defs_in: Dict[Optional[int], Dict[str, ast.AST]] = {}

    def visit(parent: ast.AST, chain: Tuple[ast.AST, ...]) -> None:
        for child in ast.iter_child_nodes(parent):
            scope_chain[id(child)] = chain
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner = id(chain[-1]) if chain else None
                defs_in.setdefault(owner, {})[child.name] = child
                visit(child, chain + (child,))
            elif isinstance(child, ast.Lambda):
                visit(child, chain + (child,))
            else:
                visit(child, chain)

    visit(tree, ())
    return scope_chain, defs_in


def _compute_traced(mod: ModuleSource) -> List[TracedFunction]:
    imports = mod.imports()
    scope_chain, defs_in = _scope_index(mod.tree)

    def resolve_def(name: str, at: ast.AST) -> Optional[ast.AST]:
        """The def a bare ``name`` at node ``at`` lexically refers to:
        innermost enclosing scope outward to module, or None (imported /
        non-def value)."""
        chain = scope_chain.get(id(at), ())
        for scope in (*reversed(chain), None):
            owner = None if scope is None else id(scope)
            d = defs_in.get(owner, {}).get(name)
            if d is not None:
                return d
        return None

    # def nodes passed to trace-entry calls → reason, resolved per call
    # site so a host-side def sharing a name with a traced closure is
    # never dragged into traced scope
    wrapped_defs: Dict[int, str] = {}

    def mark_wrapped(name: str, at: ast.AST, reason: str) -> None:
        d = resolve_def(name, at)
        if d is not None and id(d) not in wrapped_defs:
            wrapped_defs[id(d)] = reason

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = _resolved_call_target(node, imports)
        kind = _trace_entry_kind(resolved)
        if kind is None:
            # functools.partial(jax.jit, ...) used as a value, not decorator
            if resolved in ("functools.partial", "partial") and node.args:
                inner = chain_of(node.args[0])
                if inner and _trace_entry_kind(resolve_chain(inner, imports)):
                    # partial(jax.jit, f, ...): only the first bound
                    # positional is the wrapped function
                    for arg in node.args[1:2]:
                        c = chain_of(arg)
                        if c and "." not in c:
                            mark_wrapped(
                                c, node,
                                f"wrapped:{resolve_chain(inner, imports)}",
                            )
            continue
        if kind == "wrapper":
            fn_args = node.args[:1]  # jax.jit(f, ...): fn is position 0
        else:
            idxs = TRACE_HOF_FN_ARGS.get(resolved, (0,))
            fn_args = [node.args[i] for i in idxs if i < len(node.args)]
        for arg in fn_args:
            # lax.switch takes a literal *sequence* of branch functions
            cands = arg.elts if isinstance(arg, (ast.List, ast.Tuple)) else [arg]
            for cand in cands:
                c = chain_of(cand)
                if c and "." not in c:
                    mark_wrapped(c, node, f"wrapped:{resolved}")
                elif isinstance(cand, ast.Call):
                    # jax.jit(functools.partial(f, ...)) — the inner f
                    inner_t = _resolved_call_target(cand, imports)
                    if inner_t in ("functools.partial", "partial") and cand.args:
                        ic = chain_of(cand.args[0])
                        if ic and "." not in ic:
                            mark_wrapped(ic, node, f"wrapped:{resolved}")

    marked: Dict[int, str] = {}
    order: List[ast.AST] = []

    def mark(node, reason):
        if id(node) in marked:
            return
        marked[id(node)] = reason
        order.append(node)
        # (c) everything lexically nested runs under the same trace;
        # each nested def's reason names its NEAREST enclosing function
        # (not the root) so closure-param accumulation stays precise
        for child in ast.walk(node):
            if child is not node and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if id(child) not in marked:
                    chain = scope_chain.get(id(child), ())
                    outer_name = (
                        getattr(chain[-1], "name", "<lambda>")
                        if chain else getattr(node, "name", "<lambda>")
                    )
                    marked[id(child)] = f"nested:{outer_name}"
                    order.append(child)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(
                _decorator_is_tracing(d, imports) for d in node.decorator_list
            ):
                mark(node, "decorator")
            elif id(node) in wrapped_defs:
                mark(node, wrapped_defs[id(node)])

    # ast.walk is breadth-first, so an enclosing fn always precedes its
    # nested defs in `order` — outer links resolve by node identity
    by_id: Dict[int, TracedFunction] = {}
    out: List[TracedFunction] = []
    for node in order:
        chain = scope_chain.get(id(node), ())
        outer = by_id.get(id(chain[-1])) if chain else None
        tf = TracedFunction(
            node, marked[id(node)], getattr(node, "name", "<lambda>"), outer,
        )
        by_id[id(node)] = tf
        out.append(tf)
    return out


def walk_body(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a traced function's subtree, including nested defs (they are
    traced too; per-def findings stay deduplicated because passes anchor
    on the node's location)."""
    yield from ast.walk(fn_node)


def params_of(fn_node: ast.AST) -> Set[str]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return set()
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def iter_py_files(
    root_dir: str, skip_dirs: Tuple[str, ...] = ("__pycache__",)
) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root_dir):
        dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)
