"""Continuous-learning promotion gate: BENCH_LOOP vs budgets.json
``loop``.

The chaos drill's loop phase (``scripts/chaos_drill.py``, phase
``loop``) rehearses the whole continuous-learning cycle against a real
fleet: incremental ingest under the CRC-stamped cursor, warm-start
continued SGNS from the latest verified checkpoint, the holdout
quality gate, a shadow-traffic canary against live load, and gated
promotion through the existing swap machinery — with a REAL SIGKILL
injected in every loop state and the cycle resumed from its journal.
Results land in ``BENCH_LOOP_r*.json``; this pass re-checks the NEWEST
committed record against the ``loop`` section of ``budgets.json`` on
every ``cli.analyze`` run — a loop that quietly starts promoting
churn-heavy candidates, dropping bit-exact resume, or serving wrong or
mixed-iteration answers through a promotion fails the analyzer exactly
like a collective-bytes regression does.

Deliberately jax-free and I/O-only (two small JSON reads): it rides
the DEFAULT tier.  A missing bench file is an *info* finding (a fresh
checkout must not fail lint before its first drill); a record that
exists and violates — or omits — a budgeted quantity, or was measured
off the pinned recipe, gates hard (the passes_obs recipe-pinning
lesson).  ``GENE2VEC_TPU_LOOP_ROOT`` overrides the artifact root for
the planted-violation fixtures.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

LOOP_ROOT_ENV = "GENE2VEC_TPU_LOOP_ROOT"
BENCH_LOOP_NAME = "BENCH_LOOP_r16.json"

_PASS = "loop-promotion-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    if isinstance(v, bool):
        return float(v)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_loop_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_LOOP_r*`` under ``root`` (highest round wins,
    mtime breaks ties) — a violating r17 must beat a stale clean r16,
    the round convention every bench family follows."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched and matched[0] == "loop":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime, path))
    if not candidates:
        return None
    return max(candidates)[2]


def loop_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the recorded loop drill against the budget."""
    budgets: Dict = load_budgets(budgets_path).get("loop", {})
    if not budgets:
        return []
    root = root or os.environ.get(LOOP_ROOT_ENV) or REPO_ROOT
    path = _newest_loop_bench(root) or os.path.join(root, BENCH_LOOP_NAME)
    label = os.path.basename(path)
    if not os.path.exists(path):
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no continuous-learning bench recorded yet ({label} "
                "missing); run `python scripts/chaos_drill.py --only "
                f"loop --loop-out {label}` to stamp one"
            ),
        )]
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable continuous-learning bench: {e}",
        )]

    findings: List[Finding] = []
    for name, budget in budgets.items():
        if name.startswith("_"):
            continue
        section = bench.get("loop")
        if not isinstance(section, dict):
            findings.append(Finding(
                pass_id=_PASS,
                path=label,
                message=(
                    f"{label} has no 'loop' results section to check "
                    f"against budget {name!r}"
                ),
            ))
            continue
        findings.extend(_check_one(name, budget, section, label))
    return findings


def _check_one(
    name: str, budget: Dict, section: Dict, label: str
) -> List[Finding]:
    data: Dict = {"budget": name}
    problems: List[str] = []

    # every budgeted quantity must be PRESENT: a record missing a field
    # must gate like a violation, or dropping the key becomes the way
    # to pass (the passes_fleet/passes_autoscale lesson)
    def bounded(key: str, bound_key: str, *, what: str) -> None:
        bound = _get(budget, bound_key)
        if bound is None:
            return
        measured = _get(section, key)
        data[key] = measured
        data[bound_key] = bound
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif measured > bound:
            problems.append(
                f"{key} {measured:g} > budget {bound:g} ({what})"
            )

    def required(key: str, require_key: str, *, what: str) -> None:
        if not budget.get(require_key):
            return
        measured = _get(section, key)
        data[key] = measured
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif measured != 1.0:
            problems.append(f"{key} is false ({what})")

    bounded(
        "answer_churn", "max_answer_churn",
        what="the promoted candidate reshuffles live answers",
    )
    bounded(
        "shadow_p99_delta_ms", "max_shadow_p99_delta_ms",
        what="the candidate arm is pathologically slower than live",
    )
    bounded(
        "wrong_answers", "max_wrong_answers",
        what="the promotion produced wrong answers",
    )
    bounded(
        "mixed_iteration_answers", "max_mixed_iteration_answers",
        what="the promotion mixed model iterations",
    )
    bounded(
        "promotion_decision_s", "max_promotion_decision_s",
        what="the shadow verdict took too long to reach the fleet",
    )
    required(
        "promoted", "require_promoted",
        what="the cycle never promoted — the loop is wedged",
    )
    required(
        "resume_bit_exact", "require_resume_bit_exact",
        what="a SIGKILL-resumed continuation diverged from the "
             "uninterrupted control",
    )
    # the budget pins the drill RECIPE — a no-kill, no-shadow run must
    # not pass a continuous-learning gate by construction
    for key in (
        "replicas", "train_iters", "shadow_sample",
        "min_shadow_requests", "states_killed",
    ):
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(section, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"drill ran with {key}={measured:g} but the budget pins "
                f"{key}={pinned:g} — re-run with the budgeted recipe"
            )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                f"continuous-learning record violates budget {name!r}: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"continuous-learning loop within budget {name!r}: "
            f"promoted with answer churn {data.get('answer_churn')}, "
            f"shadow p99 delta {data.get('shadow_p99_delta_ms')} ms, "
            "zero wrong/mixed answers, bit-exact resume through "
            f"{data.get('states_killed')} injected SIGKILLs"
        ),
        data=data,
    )]
