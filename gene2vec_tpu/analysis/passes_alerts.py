"""Alert-detection budget gate: BENCH_ALERTS vs budgets.json ``alerts``.

The chaos drill's alerting phase (``scripts/chaos_drill.py``, phase
``alerts``) injects faults into one replica of a live fleet and
measures the detection loop end to end: how long until the right rule
fires (``alert_detection_latency_s``), whether any rule fired during
the clean warmup (false positives), and whether the auto-assembled
incident bundle is manifest-CRC-verified and contains a reassembled
trace through the faulty replica.  Results land in
``BENCH_ALERTS_r*.json``; this pass re-checks the NEWEST committed
record against the ``alerts`` section of ``budgets.json`` on every
``cli.analyze`` run — detection latency that quietly erodes, or a
drill rerun stamping false positives, fails the analyzer exactly like
a collective-bytes regression does.

Deliberately jax-free and I/O-only (two small JSON reads): it rides
the DEFAULT tier.  A missing bench file is an *info* finding (a fresh
checkout must not fail lint before its first drill); a record that
exists and violates — or omits — a budgeted quantity, or was measured
off the pinned recipe, gates hard (the passes_obs recipe-pinning
lesson).  ``GENE2VEC_TPU_ALERTS_ROOT`` overrides the artifact root for
the planted-violation fixtures.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

ALERTS_ROOT_ENV = "GENE2VEC_TPU_ALERTS_ROOT"
BENCH_ALERTS_NAME = "BENCH_ALERTS_r13.json"

_PASS = "alerts-detection-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_alerts_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_ALERTS_r*`` under ``root`` (highest round
    wins, mtime breaks ties) — a violating r14 must beat a stale clean
    r13, the round convention every bench family follows."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched and matched[0] == "alerts":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime, path))
    if not candidates:
        return None
    return max(candidates)[2]


def alerts_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the recorded alert-detection drill against the budget."""
    budgets: Dict = load_budgets(budgets_path).get("alerts", {})
    if not budgets:
        return []
    root = root or os.environ.get(ALERTS_ROOT_ENV) or REPO_ROOT
    path = _newest_alerts_bench(root) or os.path.join(
        root, BENCH_ALERTS_NAME
    )
    label = os.path.basename(path)
    if not os.path.exists(path):
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no alert-detection bench recorded yet ({label} "
                "missing); run `python scripts/chaos_drill.py --only "
                f"alerts --alerts-out {label}` to stamp one"
            ),
        )]
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable alert-detection bench: {e}",
        )]

    findings: List[Finding] = []
    for name, budget in budgets.items():
        if name.startswith("_"):
            continue
        section = bench.get("alerts")
        if not isinstance(section, dict):
            findings.append(Finding(
                pass_id=_PASS,
                path=label,
                message=(
                    f"{label} has no 'alerts' results section to check "
                    f"against budget {name!r}"
                ),
            ))
            continue
        findings.extend(_check_one(name, budget, section, label))
    return findings


def _check_one(
    name: str, budget: Dict, section: Dict, label: str
) -> List[Finding]:
    latency = _get(section, "detection_latency_s")
    false_pos = _get(section, "warmup_false_positives")
    verified = section.get("bundle_verified")
    trace_ok = section.get("bundle_trace_through_faulty_replica")
    max_latency = float(budget["max_detection_latency_s"])
    data = {
        "budget": name,
        "detection_latency_s": latency,
        "max_detection_latency_s": max_latency,
        "warmup_false_positives": false_pos,
        "bundle_verified": verified,
        "bundle_trace_through_faulty_replica": trace_ok,
    }
    # every budgeted quantity must be PRESENT: a record missing a field
    # must gate like a violation, or dropping the key becomes the way
    # to pass (the passes_fleet lesson)
    problems: List[str] = []
    if latency is None:
        problems.append("detection_latency_s missing from the bench record")
    elif latency > max_latency:
        problems.append(
            f"detection latency {latency:.2f}s > budget {max_latency:g}s "
            "(the fleet noticed its own fault too slowly)"
        )
    max_fp = float(budget.get("max_false_positives", 0))
    if false_pos is None:
        problems.append(
            "warmup_false_positives missing from the bench record"
        )
    elif false_pos > max_fp:
        problems.append(
            f"{int(false_pos)} rule(s) fired during the CLEAN warmup "
            f"(budget {int(max_fp)}) — the rules are too twitchy to "
            "page on"
        )
    if budget.get("require_bundle_verified", True) and verified is not True:
        problems.append(
            "incident bundle was not manifest-CRC-verified "
            f"(bundle_verified={verified!r})"
        )
    if budget.get(
        "require_trace_through_faulty_replica", True
    ) and trace_ok is not True:
        problems.append(
            "no reassembled bundle trace passes through the faulty "
            f"replica (bundle_trace_through_faulty_replica={trace_ok!r})"
        )
    # the budget pins the drill RECIPE — a one-replica no-load run must
    # not pass a detection-latency gate by construction
    for key in ("replicas", "scrape_interval_s", "proxy_attempts"):
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(section, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"drill ran with {key}={measured:g} but the budget pins "
                f"{key}={pinned:g} — re-run with the budgeted recipe"
            )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                f"alert-detection record violates budget {name!r}: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"alert detection latency {latency:.2f}s within budget "
            f"{name!r} (<= {max_latency:g}s), {int(false_pos)} warmup "
            "false positive(s), bundle verified"
        ),
        data=data,
    )]
