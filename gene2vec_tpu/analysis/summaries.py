"""Round-summary claim checking: numbers in docs vs ground truth.

Round summaries (docs/ROUND*_SUMMARY.md) state test counts — "159 → 163
tests", "171 passed" — that readers use to judge whether a round kept
the suite healthy.  A misstatement there cost a manual audit in round 5
("159 → 170+" vs 163 actually collected), so this pass cross-checks
every test-count claim against the live ``pytest --collect-only -q``
count where feasible.

Feasibility rule: suites only grow across rounds, so a historical claim
is *checkable* as an upper bound — a summary may claim at most as many
tests as exist today.  (An exact per-round check would need a checkout
of that round's commit; the tier-1 test takes the cheap invariant.)
"""

from __future__ import annotations

import glob
import os
import re
from typing import Iterator, List, Optional

from gene2vec_tpu.analysis.findings import Finding

#: "159 → 163 tests", "-> 170+ tests green", "163 tests collected",
#: "171 passed" — the arrow form's right-hand side is the claim
_ARROW_CLAIM = re.compile(
    r"(\d+)\s*(?:→|->)\s*(\d+)(\+?)\s*tests", re.UNICODE
)
_FLAT_CLAIM = re.compile(r"(\d+)(\+?)\s+tests\b")
_PASSED_CLAIM = re.compile(r"(\d+)\s+(?:tests\s+)?passed\b")


def iter_claims(text: str, path: str) -> Iterator[Finding]:
    """Every test-count claim in ``text`` as an *info* finding; the
    caller (or :func:`check_summaries`) upgrades violations."""
    for lineno, line in enumerate(text.splitlines(), 1):
        spans = []
        for m in _ARROW_CLAIM.finditer(line):
            spans.append((m.span(), int(m.group(2)), bool(m.group(3))))
        for m in _FLAT_CLAIM.finditer(line):
            # skip flat matches inside an arrow claim's span
            if any(s[0] <= m.start() < s[1] for (s, _, _) in spans):
                continue
            spans.append((m.span(), int(m.group(1)), bool(m.group(2))))
        for m in _PASSED_CLAIM.finditer(line):
            if any(s[0] <= m.start() < s[1] for (s, _, _) in spans):
                continue
            spans.append((m.span(), int(m.group(1)), False))
        for _, count, at_least in spans:
            yield Finding(
                pass_id="summary-claims",
                severity="info",
                path=path,
                line=lineno,
                message=f"test-count claim: {count}{'+' if at_least else ''}",
                snippet=line.strip(),
                data={"claimed": count, "at_least": at_least},
            )


def check_summaries(
    docs_dir: str, collected_count: Optional[int]
) -> List[Finding]:
    """Cross-check every ROUND*_SUMMARY.md claim against the collected
    test count.  ``collected_count=None`` (count unavailable — e.g. a
    partial test invocation) returns the claims as info findings only.
    """
    findings: List[Finding] = []
    for path in sorted(glob.glob(os.path.join(docs_dir, "ROUND*_SUMMARY.md"))):
        rel = os.path.join("docs", os.path.basename(path))
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        for claim in iter_claims(text, rel):
            claimed = claim.data["claimed"]
            if collected_count is not None and claimed > collected_count:
                findings.append(Finding(
                    pass_id="summary-claims",
                    severity="error",
                    path=claim.path,
                    line=claim.line,
                    message=(
                        f"summary claims {claimed} tests but only "
                        f"{collected_count} are collected — suites only "
                        "grow across rounds, so this claim can never have "
                        "been true"
                    ),
                    snippet=claim.snippet,
                    data={"claimed": claimed, "collected": collected_count},
                ))
            else:
                findings.append(claim)
    return findings


def collect_count_via_pytest(repo_root: str, timeout: int = 300) -> Optional[int]:
    """``pytest --collect-only -q`` as a subprocess → collected count,
    or None when collection fails/times out.  Heavyweight (imports the
    whole test suite); the tier-1 test reads the live session's count
    from tests/conftest.py instead."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [
                sys.executable, "-m", "pytest", "tests/", "--collect-only",
                "-q", "-p", "no:cacheprovider",
            ],
            cwd=repo_root, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except Exception:
        return None
    m = None
    for m in re.finditer(r"(\d+) tests collected", proc.stdout):
        pass
    return int(m.group(1)) if m else None
