"""Tier-1 AST lint passes: JAX footguns + the stdout-discipline lint.

Every pass is cheap (pure ``ast``, no jax import) and runs inside tier-1
via tests/test_analysis.py.  Pass semantics, rationale, and the exact
false-positive trade-offs are documented in docs/STATIC_ANALYSIS.md; the
planted-violation fixtures in tests/test_analysis.py pin each pass to
fire exactly once on its fixture and never on the package at HEAD.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from gene2vec_tpu.analysis.astpass import (
    ModuleSource,
    TracedFunction,
    chain_of,
    import_table,
    is_jit_chain,
    params_of,
    resolve_chain,
    traced_functions,
)
from gene2vec_tpu.analysis.findings import Finding

#: attribute calls that force a device→host sync (or are host-only)
_HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready", "copy_to_host_async"}

#: builtins that coerce a traced value to a Python scalar
_SCALAR_COERCIONS = {"float", "int", "bool", "complex"}

_TRAINY_NAME = re.compile(r"(?:^|_)(train|epoch|step|update)")
_DONATE_EXEMPT = re.compile(r"(init|predict|eval|loss|infer|metric)")


class Pass:
    """Base: subclasses set ``id``/``title``/``severity``/``roots`` and
    implement :meth:`run` over one module."""

    id: str = ""
    title: str = ""
    severity: str = "error"
    #: which file sets the runner feeds this pass ("package",
    #: "experiments"); the cli layer is excluded per-pass via applies()
    roots = ("package",)

    def applies(self, rel: str) -> bool:
        return True

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleSource, node: ast.AST, message: str,
                severity: Optional[str] = None, data=None) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(
            pass_id=self.id,
            message=message,
            path=mod.rel,
            line=line,
            col=getattr(node, "col_offset", 0),
            severity=severity or self.severity,
            snippet=mod.line(line),
            data=data,
        )


def _iter_own_body(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's subtree but stop at nested function boundaries
    (nested defs get their own TracedFunction entry)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class TracedScopePass(Pass):
    """Shared driver for passes that inspect traced function bodies:
    resolves traced scopes once and hands each (function, visible
    parameter set) to :meth:`check`."""

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        traced = traced_functions(mod)
        if not traced:
            return
        imports = mod.imports()
        # visible params accumulate outer → nested (closure variables of
        # an enclosing traced fn are still traced values inside a nested
        # one); linked by node identity via tf.outer — never by name,
        # which would cross wires between same-named functions
        visible: Dict[int, Set[str]] = {}
        for tf in traced:
            base: Set[str] = set()
            if tf.outer is not None:
                base = visible.get(id(tf.outer.node), set())
            visible[id(tf.node)] = base | params_of(tf.node)
            yield from self.check(mod, imports, tf, visible[id(tf.node)])

    def check(
        self, mod: ModuleSource, imports: Dict[str, str],
        tf: TracedFunction, params: Set[str],
    ) -> Iterator[Finding]:
        raise NotImplementedError


class HostSyncInJitPass(TracedScopePass):
    """Host-sync calls inside traced code: ``.item()`` / ``.tolist()`` /
    ``block_until_ready()``, ``np.*`` calls on non-constant values, and
    ``float()/int()/bool()`` applied to traced parameters.  Under ``jit``
    these either fail with a tracer error at runtime or (worse, under
    ``io_callback``-style escapes) silently serialize the device stream.
    """

    id = "host-sync-in-jit"
    title = "host synchronization inside jit/scan"

    def check(self, mod, imports, tf, params):
        for node in _iter_own_body(tf.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _HOST_SYNC_ATTRS:
                yield self.finding(
                    mod, node,
                    f".{fn.attr}() inside traced function "
                    f"'{tf.name}' forces a device->host sync "
                    f"(traced via {tf.reason})",
                )
                continue
            chain = chain_of(fn)
            if chain is None:
                continue
            resolved = resolve_chain(chain, imports)
            if resolved.startswith("numpy."):
                args_have_names = any(
                    _names_in(a) for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                )
                if args_have_names:
                    yield self.finding(
                        mod, node,
                        f"numpy call {chain}(...) on non-constant values "
                        f"inside traced function '{tf.name}' runs on host "
                        "(tracer error or silent constant-folding); use "
                        "jax.numpy",
                    )
            elif chain in _SCALAR_COERCIONS and node.args:
                if _names_in(node.args[0]) & params:
                    yield self.finding(
                        mod, node,
                        f"{chain}() coerces a traced value to a Python "
                        f"scalar inside traced function '{tf.name}'",
                    )


class PythonRNGInTracePass(TracedScopePass):
    """Python-side RNG (``random``, ``np.random``) inside traced code:
    the draw happens once at trace time and is baked into the compiled
    program as a constant — every execution reuses the same "random"
    numbers.  Use ``jax.random`` with explicit keys."""

    id = "py-rng-in-trace"
    title = "host RNG inside traced code"

    def check(self, mod, imports, tf, params):
        for node in _iter_own_body(tf.node):
            if not isinstance(node, ast.Call):
                continue
            chain = chain_of(node.func)
            if chain is None:
                continue
            resolved = resolve_chain(chain, imports)
            if resolved.startswith("numpy.random.") or (
                resolved.startswith("random.") and resolved.count(".") == 1
            ):
                yield self.finding(
                    mod, node,
                    f"host RNG {chain}(...) inside traced function "
                    f"'{tf.name}' is drawn once at trace time and baked "
                    "into the compiled program; use jax.random",
                )


class TracerLeakPass(TracedScopePass):
    """Assignments to instance or global state inside traced code leak
    tracers out of the trace: the stored object is a ``Tracer`` that
    escapes its trace context and poisons later computations (JAX raises
    ``UnexpectedTracerError`` only when it is *used*, far from the
    leak)."""

    id = "tracer-leak"
    title = "tracer leaked into instance/global state"

    def check(self, mod, imports, tf, params):
        for node in _iter_own_body(tf.node):
            if isinstance(node, ast.Global):
                yield self.finding(
                    mod, node,
                    f"global statement inside traced function '{tf.name}' "
                    "— assigning module state under a trace leaks tracers",
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    chain = chain_of(t)
                    if chain and chain.startswith("self."):
                        yield self.finding(
                            mod, node,
                            f"assignment to {chain} inside traced function "
                            f"'{tf.name}' stores a tracer on the instance; "
                            "return the value through the traced outputs "
                            "instead",
                        )


class JitRecompileHazardPass(Pass):
    """Jit cache-miss hazards detectable lexically:

    * ``jax.jit(f, ...)(args)`` — a wrapper constructed and invoked in
      one expression is a fresh callable every execution, so it misses
      the jit cache unconditionally (recompiles per call; the viz/tsne
      docstring measured minutes-vs-seconds over device tunnels);
    * a dict/set literal passed at a jitted call site — dict structure
      is part of the cache key (changing keys recompile) and unhashable
      as a static argument.
    """

    id = "jit-recompile-hazard"
    title = "jit recompilation hazard"

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        imports = mod.imports()

        # names bound to jitted callables in this module
        jitted_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                resolved = resolve_chain(
                    chain_of(node.value.func) or "", imports
                )
                if is_jit_chain(resolved):
                    for t in node.targets:
                        c = chain_of(t)
                        if c:
                            jitted_names.add(c)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dchain = chain_of(
                        dec.func if isinstance(dec, ast.Call) else dec
                    )
                    if dchain is None and isinstance(dec, ast.Call):
                        continue
                    resolved = resolve_chain(dchain or "", imports)
                    if is_jit_chain(resolved):
                        jitted_names.add(node.name)
                    elif (
                        isinstance(dec, ast.Call)
                        and resolved in ("functools.partial", "partial")
                        and dec.args
                        and is_jit_chain(
                            resolve_chain(chain_of(dec.args[0]) or "", imports)
                        )
                    ):
                        jitted_names.add(node.name)

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # jax.jit(...)(...) immediately invoked
            if isinstance(node.func, ast.Call):
                resolved = resolve_chain(
                    chain_of(node.func.func) or "", imports
                )
                if is_jit_chain(resolved):
                    yield self.finding(
                        mod, node,
                        "jax.jit(...) constructed and invoked in one "
                        "expression: a fresh wrapper misses the jit cache "
                        "every call (recompiles); bind the jitted function "
                        "once at module or __init__ scope",
                    )
                    continue
            callee = chain_of(node.func)
            if callee in jitted_names:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, (ast.Dict, ast.Set, ast.DictComp,
                                        ast.SetComp)):
                        yield self.finding(
                            mod, arg,
                            f"dict/set literal passed to jitted '{callee}': "
                            "its structure is part of the jit cache key "
                            "(key changes recompile) and it is unhashable "
                            "as a static argument; pass arrays or a "
                            "stable-structure pytree",
                        )


class MissingDonatePass(Pass):
    """Trainer-shaped jit entry points (name matching train/epoch/step/
    update) that thread large parameter pytrees through every call should
    donate them — without ``donate_argnums`` XLA double-buffers the
    tables (2x HBM for the SGNS tables at the 24k-vocab scale).
    init/predict/eval-named functions are exempt (their buffers are
    genuinely consumed by the caller).  Severity ``warning`` records that
    this is a *name heuristic* — it still gates (``findings.gating``
    treats error and warning alike); a legitimately-non-donating match is
    silenced at the site with ``# graftcheck: disable=missing-donate``,
    never by weakening the pass or the repo gate."""

    id = "missing-donate"
    title = "large-param jit entry point without donate_argnums"
    severity = "warning"

    def _check_kwargs(self, call: ast.Call) -> bool:
        return any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in call.keywords
        )

    def _wrapped_name(self, arg: ast.AST, imports) -> Optional[str]:
        c = chain_of(arg)
        if c is not None:
            return c.split(".")[-1]
        if isinstance(arg, ast.Call):
            resolved = resolve_chain(chain_of(arg.func) or "", imports)
            if resolved in ("functools.partial", "partial") and arg.args:
                inner = chain_of(arg.args[0])
                if inner:
                    return inner.split(".")[-1]
        return None

    def _name_gated(self, name: Optional[str]) -> bool:
        return bool(
            name
            and _TRAINY_NAME.search(name)
            and not _DONATE_EXEMPT.search(name)
        )

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        imports = mod.imports()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                resolved = resolve_chain(chain_of(node.func) or "", imports)
                if is_jit_chain(resolved) and node.args:
                    name = self._wrapped_name(node.args[0], imports)
                    if self._name_gated(name) and not self._check_kwargs(node):
                        yield self.finding(
                            mod, node,
                            f"jax.jit({name}, ...) looks like a training "
                            "entry point but does not donate its parameter "
                            "buffers (donate_argnums) — XLA will "
                            "double-buffer the tables",
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not self._name_gated(node.name):
                    continue
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        # bare @jax.jit on a trainy name: no kwargs at all
                        resolved = resolve_chain(chain_of(dec) or "", imports)
                        if is_jit_chain(resolved):
                            yield self.finding(
                                mod, dec,
                                f"@jit on '{node.name}' without "
                                "donate_argnums — training entry points "
                                "should donate their parameter buffers",
                            )
                        continue
                    resolved = resolve_chain(chain_of(dec.func) or "", imports)
                    is_jit_dec = is_jit_chain(resolved) or (
                        resolved in ("functools.partial", "partial")
                        and dec.args
                        and is_jit_chain(
                            resolve_chain(chain_of(dec.args[0]) or "", imports)
                        )
                    )
                    if is_jit_dec and not self._check_kwargs(dec):
                        yield self.finding(
                            mod, dec,
                            f"jit decorator on '{node.name}' without "
                            "donate_argnums — training entry points should "
                            "donate their parameter buffers",
                        )


class BarePrintPass(Pass):
    """No bare ``print()`` outside the cli layer (absorbs
    scripts/check_no_bare_prints.py; that script is now a shim over this
    pass).  Library modules emit through ``gene2vec_tpu.obs``, an
    injected ``log`` callable, or an explicit ``file=`` stream — a bare
    print writes to stdout, which CLI contracts own (bench.py prints
    exactly ONE JSON line on stdout).  Extended to ``experiments/``:
    probe scripts route progress chatter to stderr and claim stdout
    explicitly when a JSON payload *is* the product."""

    id = "bare-print"
    title = "bare print() outside the cli layer"
    roots = ("package", "experiments")

    def applies(self, rel: str) -> bool:
        parts = rel.replace("\\", "/").split("/")
        return "cli" not in parts

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Name) and fn.id == "print"):
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue
            yield self.finding(
                mod, node,
                "bare print() writes to stdout, which CLI contracts own — "
                "route through gene2vec_tpu.obs, a log callable, or an "
                "explicit file= stream",
            )


#: disk-touching calls forbidden on an async submit path (resolved
#: through the module's import table, so ``np.savez`` matches too)
_BLOCKING_IO_CHAINS = {
    "os.fsync", "os.fdatasync", "os.replace", "os.rename", "os.link",
    "numpy.save", "numpy.savez", "numpy.savez_compressed",
    "json.dump", "shutil.copy", "shutil.copyfile", "shutil.move",
}


class CkptBlockingIOPass(Pass):
    """No blocking disk I/O on an async writer's submit path.

    The resilience async-checkpoint contract (docs/RESILIENCE.md): a
    ``submit``/``submit_*`` method is the producer side of a
    staging-queue handoff — the train loop (or request path) calls it
    every cycle, and its whole point is that the expensive work happens
    on the consumer thread.  A file ``open()``, an ``os.fsync``/
    ``os.replace``, an ``np.savez`` or a ``.block_until_ready()``
    sneaking into a submit body silently re-serializes the caller on
    disk (or device) latency — exactly the stall the async writer
    exists to remove, and invisible in tests that use tiny tables.
    Heavy lifting belongs in the closure the submit *enqueues* (a
    lambda/def handed over is a nested scope, which this pass does not
    descend into) or on the worker thread.
    """

    id = "ckpt-blocking-io"
    title = "blocking disk I/O on an async submit hot path"

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        imports = mod.imports()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (node.name == "submit" or node.name.startswith("submit_")):
                continue
            for sub in _iter_own_body(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if isinstance(fn, ast.Name) and fn.id == "open":
                    yield self.finding(
                        mod, sub,
                        f"open() inside '{node.name}' blocks the submit "
                        "hot path on disk; move file I/O into the "
                        "enqueued closure / writer thread",
                    )
                    continue
                if isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready":
                    yield self.finding(
                        mod, sub,
                        f".block_until_ready() inside '{node.name}' "
                        "serializes the submit hot path on the device "
                        "stream; stage the host copy and return",
                    )
                    continue
                chain = chain_of(fn)
                if chain is None:
                    continue
                resolved = resolve_chain(chain, imports)
                if resolved in _BLOCKING_IO_CHAINS:
                    yield self.finding(
                        mod, sub,
                        f"{chain}(...) inside '{node.name}' blocks the "
                        "submit hot path on disk; move it into the "
                        "enqueued closure / writer thread",
                    )


class SpanHygienePass(Pass):
    """Tracing-span discipline for the obs layer:

    * **no span enter/exit inside jitted/traced code** — a span context
      manager opening inside a traced function runs at TRACE time, not
      execution time: the recorded duration is compilation, every
      execution after the first records nothing, and the file write is
      a host side effect inside a trace (the same class of bug as
      host-sync-in-jit);
    * **no span left unclosed on early return** — ``ambient_span(...)``
      / ``<tracer>.span(...)`` are context managers; calling one
      without ``with`` (an expression statement, an assignment) never
      runs ``__exit__`` on an early return or exception, leaving an
      unterminated ``span_start`` in the timeline and a corrupted
      parent stack for every later span on that thread.  The one
      sanctioned non-``with`` form is ``return <span call>`` — the
      thin-wrapper pattern (``Run.span``) hands the unopened manager to
      a caller who ``with``-s it.

    The ``.span`` attribute form is only checked in modules that import
    ``gene2vec_tpu.obs`` (a regex ``m.span()`` in unrelated code must
    not trip it); the distinctive ``ambient_span`` name is always
    checked.  ``hop_span`` is a plain function, not a manager, and is
    exempt.
    """

    id = "span-hygiene"
    title = "obs span misuse (span in traced code / span not closed)"

    def _is_span_call(self, node: ast.Call, imports: Dict[str, str],
                      attr_form_ok: bool) -> bool:
        fn = node.func
        chain = chain_of(fn)
        if chain is not None:
            resolved = resolve_chain(chain, imports)
            if chain == "ambient_span" or resolved.endswith(
                ".ambient_span"
            ):
                return True
        if attr_form_ok and isinstance(fn, ast.Attribute):
            return fn.attr == "span"
        return False

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        imports = mod.imports()
        uses_obs = any(
            v == "gene2vec_tpu.obs" or v.startswith("gene2vec_tpu.obs.")
            for v in imports.values()
        )
        traced = traced_functions(mod)
        traced_nodes: Set[int] = set()
        for tf in traced:
            for node in _iter_own_body(tf.node):
                traced_nodes.add(id(node))
                if isinstance(node, ast.Call) and self._is_span_call(
                    node, imports, attr_form_ok=uses_obs
                ):
                    yield self.finding(
                        mod, node,
                        f"span enter/exit inside traced function "
                        f"'{tf.name}' (traced via {tf.reason}): the span "
                        "runs at trace time and its file write is a host "
                        "side effect inside the compiled program — time "
                        "the call site instead",
                    )
        if not uses_obs:
            return
        # rule 2: span context managers must be entered via `with` (or
        # returned by a thin wrapper); anything else leaks the span on
        # early return.  Traced bodies are rule 1's jurisdiction.
        allowed: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    allowed.add(id(item.context_expr))
            elif isinstance(node, ast.Return) and node.value is not None:
                allowed.add(id(node.value))
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and id(node) not in allowed
                and id(node) not in traced_nodes
                and self._is_span_call(node, imports, attr_form_ok=True)
            ):
                yield self.finding(
                    mod, node,
                    "span context manager created without `with`: on an "
                    "early return or exception __exit__ never runs, "
                    "leaving an unterminated span_start and a corrupted "
                    "parent stack; use `with ... span(...)` (or return "
                    "it from a thin wrapper)",
                )


#: event-loop callback naming convention (serve/eventloop.py: the
#: selector dispatch targets `_on_accept`/`_on_readable`/...); signal
#: handlers (`_on_term`, `_on_sigquit`) share the convention and the
#: same no-blocking discipline applies to them
_EVENTLOOP_CALLBACK = re.compile(r"^_?on_[a-z0-9_]+$")

#: attribute calls that block (or can block) the calling thread on
#: socket I/O — inside a loop callback these stall EVERY connection
_EVENTLOOP_BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "makefile"}


class EventLoopBlockingPass(Pass):
    """No blocking calls inside event-loop callbacks.

    The serve front end (``serve/eventloop.py``) runs every
    connection's protocol work on one selector loop; its dispatch
    targets follow the ``on_*``/``_on_*`` naming convention.  A
    ``time.sleep``, a blocking ``socket.recv``/``sendall``, or a
    ``json.dumps`` of a response body inside one of those callbacks
    stalls EVERY connection on the loop for the duration — the exact
    head-of-line blocking the event loop exists to remove, and
    invisible under single-connection tests.  Raw socket I/O belongs in
    the non-blocking ``_fill``/``_flush`` I/O-path helpers (which this
    pass does not scan — they are not callbacks), sleeps belong on
    worker-pool threads, and response bodies are pre-encoded off-loop
    (the zero-copy contract: a hot response is reused bytes, never a
    per-request ``json.dumps``).

    The name scope is a heuristic (signal handlers like ``_on_term``
    share the convention — and the same discipline); a legitimate
    blocking call in an ``on_*`` function is silenced at the site with
    ``# graftcheck: disable=event-loop-blocking``, never by weakening
    the pass."""

    id = "event-loop-blocking"
    title = "blocking call inside an event-loop callback"

    def run(self, mod: ModuleSource) -> Iterator[Finding]:
        imports = mod.imports()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _EVENTLOOP_CALLBACK.match(node.name):
                continue
            for sub in _iter_own_body(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _EVENTLOOP_BLOCKING_ATTRS
                ):
                    yield self.finding(
                        mod, sub,
                        f".{fn.attr}() inside event-loop callback "
                        f"'{node.name}' can block the loop thread, "
                        "stalling every connection; raw socket I/O "
                        "belongs in the non-blocking _fill/_flush "
                        "I/O-path helpers",
                    )
                    continue
                chain = chain_of(fn)
                if chain is None:
                    continue
                resolved = resolve_chain(chain, imports)
                if resolved == "time.sleep":
                    yield self.finding(
                        mod, sub,
                        f"time.sleep(...) inside event-loop callback "
                        f"'{node.name}' stalls every connection on "
                        "this loop; sleeps (fault delays, backoff) "
                        "belong on worker-pool threads",
                    )
                elif resolved in ("json.dumps", "json.dump"):
                    yield self.finding(
                        mod, sub,
                        f"{chain}(...) inside event-loop callback "
                        f"'{node.name}' serializes a body on the loop "
                        "thread; pre-encode responses off-loop and "
                        "hand the loop reusable bytes (the zero-copy "
                        "contract)",
                    )


_PROFILER_TIME_CALLS = {
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.process_time",
    "time.time",
}


class ProfilerHookInJitPass(TracedScopePass):
    """Profiling/timing hooks inside traced code: ``time.perf_counter``
    and friends, ``kernel_span`` wrappers, and compiled-computation
    ``cost_analysis`` probes.  Inside a traced body the clock is read
    ONCE at trace time and baked into the compiled program as a
    constant — the "measurement" never moves again — and a
    cost-analysis hook traced into the program recompiles it.  The
    attribution plane (obs/profiler.py) is warm-time/epoch-level by
    contract (budgets.json ``kernels.profile``): attribute at compile,
    observe OUTSIDE the traced step, never per batch inside the scan.
    """

    id = "profiler-hook-in-jit"
    title = "profiling/timing hook inside jit/scan"

    def check(self, mod, imports, tf, params):
        for node in _iter_own_body(tf.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "cost_analysis":
                yield self.finding(
                    mod, node,
                    f".cost_analysis() inside traced function "
                    f"'{tf.name}' traces the probe into the compiled "
                    "program (traced via "
                    f"{tf.reason}); attribute AOT at warm time "
                    "(obs/profiler.py KernelProfiler.attribute)",
                )
                continue
            chain = chain_of(fn)
            if chain is None:
                continue
            resolved = resolve_chain(chain, imports)
            if resolved in _PROFILER_TIME_CALLS:
                yield self.finding(
                    mod, node,
                    f"{chain}() inside traced function '{tf.name}' "
                    "reads the host clock once at trace time and bakes "
                    "it in as a constant (traced via "
                    f"{tf.reason}); time the call site outside the "
                    "trace and feed KernelProfiler.observe",
                )
            elif chain.split(".")[-1] == "kernel_span":
                yield self.finding(
                    mod, node,
                    f"kernel_span(...) inside traced function "
                    f"'{tf.name}' puts the attribution hook on the "
                    "traced path (traced via "
                    f"{tf.reason}); kernel attribution is warm-time/"
                    "epoch-level, never per-batch inside the scan",
                )


ALL_PASSES = (
    BarePrintPass(),
    HostSyncInJitPass(),
    PythonRNGInTracePass(),
    TracerLeakPass(),
    JitRecompileHazardPass(),
    MissingDonatePass(),
    CkptBlockingIOPass(),
    SpanHygienePass(),
    EventLoopBlockingPass(),
    ProfilerHookInJitPass(),
)
