"""Tier-2 jaxpr/HLO invariant checks: compile the hot paths, assert budgets.

These compile the SGNS step, CBOW-HS step, and GGIPNN train step on the
(virtual 8-device) CPU backend and check:

* **no host callbacks** — no ``*callback*`` custom-calls, infeed/outfeed,
  or host transfers in the optimized module (a host callback inside the
  epoch scan serializes the device stream);
* **dtype discipline** — no f64 anywhere (an accidental
  ``jax_enable_x64`` or a float64 numpy constant upcasts the whole
  program), and no half-precision types in an f32-configured program
  (a silent downcast loses the partition sums tsne/step docs budget
  for);
* **jit cache stability** — repeated calls with fresh identically-shaped
  inputs must not recompile (cache-key hazards: unhashable statics,
  weak-type drift, non-pytree aux args);
* **collective budgets** — per-step collective bytes per mesh config
  from ``budgets.json``, the enforced version of
  ``scripts/hlo_comm_audit.py`` (obs.probes does the scanning), so the
  config-5 22.7 KB/pair regression class cannot land silently.

Everything here imports jax lazily and is marked ``slow`` in the test
suite; ``scripts/run_static_analysis.sh`` is the standalone driver.
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from gene2vec_tpu.analysis.findings import Finding

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "budgets.json")

_SHAPE_DTYPE_RE = re.compile(r"\b(pred|[fsu]\d+|bf16)\[")

#: host-callback custom-call targets (jax python callbacks, ffi callbacks)
_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="[^"]*(callback|py_func|host)[^"]*"', re.IGNORECASE
)
_HOST_OP_RE = re.compile(r"^\s*\S+\s*=\s*\S+\s+(infeed|outfeed)\(")
_HOST_TRANSFER_RE = re.compile(
    r"\b(send|recv|send-done|recv-done)\(.*is_host_transfer=true"
)


# -- HLO text checks --------------------------------------------------------


def dtype_census(hlo_text: str) -> Dict[str, int]:
    """Occurrence count of every scalar dtype appearing in HLO shapes."""
    census: Dict[str, int] = {}
    for m in _SHAPE_DTYPE_RE.finditer(hlo_text):
        census[m.group(1)] = census.get(m.group(1), 0) + 1
    return census


def host_callback_findings(hlo_text: str, label: str) -> List[Finding]:
    out = []
    for lineno, line in enumerate(hlo_text.splitlines(), 1):
        if (
            _CALLBACK_TARGET_RE.search(line)
            or _HOST_OP_RE.search(line)
            or _HOST_TRANSFER_RE.search(line)
        ):
            out.append(Finding(
                pass_id="hlo-host-callback",
                message=(
                    "host callback / host transfer in the compiled hot "
                    "path — the device stream serializes on the host at "
                    "every step"
                ),
                path=label,
                line=lineno,
                snippet=line.strip()[:160],
            ))
    return out


def dtype_findings(
    hlo_text: str,
    label: str,
    compute_dtype: str = "float32",
    forbid_f64: bool = True,
) -> List[Finding]:
    """Dtype-discipline findings + one info finding with the census."""
    census = dtype_census(hlo_text)
    out: List[Finding] = [Finding(
        pass_id="hlo-dtype",
        severity="info",
        path=label,
        message="dtype census",
        data={"census": census, "compute_dtype": compute_dtype},
    )]
    if forbid_f64 and census.get("f64"):
        out.append(Finding(
            pass_id="hlo-dtype",
            path=label,
            message=(
                f"f64 appears {census['f64']}x in the optimized module — "
                "an unintended f32->f64 upcast (x64 mode or a float64 "
                "host constant) doubles bytes on every affected tensor"
            ),
            data={"census": census},
        ))
    if compute_dtype == "float32":
        for half in ("bf16", "f16"):
            if census.get(half):
                out.append(Finding(
                    pass_id="hlo-dtype",
                    path=label,
                    message=(
                        f"{half} appears {census[half]}x in an "
                        "f32-configured program — a silent downcast "
                        "(reductions lose the partition function at "
                        "corpus scale)"
                    ),
                    data={"census": census},
                ))
    return out


# -- jit cache stability ----------------------------------------------------


def cache_stability_findings(
    fn: Callable,
    args_maker: Callable[[], Tuple],
    label: str,
    calls: int = 3,
) -> List[Finding]:
    """Call ``fn`` ``calls`` times with fresh identically-shaped inputs
    from ``args_maker``; after the warm-up call the jit cache must not
    grow (a growth means every production step would recompile)."""
    import jax

    size = getattr(fn, "_cache_size", None)
    out = jax.block_until_ready(fn(*args_maker()))
    del out
    after_warmup = size() if size is not None else None
    for _ in range(calls - 1):
        jax.block_until_ready(fn(*args_maker()))
    if size is None:
        # data.checked=False lets callers distinguish this skip from a
        # real pass — tests assert on it so a jax upgrade that removes
        # the introspection hook cannot vacuously satisfy the gate
        return [Finding(
            pass_id="hlo-cache-stability",
            severity="info",
            path=label,
            message="jit cache size introspection unavailable on this "
                    "jax version; stability not checked",
            data={"checked": False},
        )]
    after = size()
    if after > after_warmup:
        return [Finding(
            pass_id="hlo-cache-stability",
            path=label,
            message=(
                f"jit cache grew {after_warmup} -> {after} across "
                f"{calls - 1} calls with fresh identically-shaped inputs "
                "— every step recompiles in production"
            ),
            data={"checked": True, "after_warmup": after_warmup,
                  "after": after},
        )]
    return [Finding(
        pass_id="hlo-cache-stability",
        severity="info",
        path=label,
        message=f"stable at {after} cached executable(s) over {calls} calls",
        data={"checked": True, "cached": after},
    )]


# -- collective budgets -----------------------------------------------------


def load_budgets(path: str = BUDGETS_PATH) -> Dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def collective_budget_findings(
    lowered_or_compiled,
    label: str,
    budget: Dict,
) -> List[Finding]:
    """Enforce one ``budgets.json`` entry: per-pair collective bytes of a
    compiled epoch must stay within ``max_bytes_per_pair``."""
    from gene2vec_tpu.obs.probes import collective_stats

    stats = collective_stats(lowered_or_compiled)
    if stats is None:
        return [Finding(
            pass_id="hlo-collective-budget",
            path=label,
            message="failed to compile/scan the module for collectives",
        )]
    batch = budget["batch_pairs"]
    bytes_per_pair = stats["total_bytes"] / batch
    data = {
        "bytes_per_pair": round(bytes_per_pair, 1),
        "max_bytes_per_pair": budget["max_bytes_per_pair"],
        "reference_bytes_per_pair": budget.get("reference_bytes_per_pair"),
        "collectives": stats["collectives"],
    }
    if bytes_per_pair > budget["max_bytes_per_pair"]:
        return [Finding(
            pass_id="hlo-collective-budget",
            path=label,
            message=(
                f"per-pair collective bytes {bytes_per_pair:,.1f} exceed "
                f"the budget {budget['max_bytes_per_pair']:,} "
                f"(reference {budget.get('reference_bytes_per_pair')}) — "
                "a comm regression of the config-5 class"
            ),
            data=data,
        )]
    return [Finding(
        pass_id="hlo-collective-budget",
        severity="info",
        path=label,
        message=(
            f"{bytes_per_pair:,.1f} bytes/pair within budget "
            f"{budget['max_bytes_per_pair']:,}"
        ),
        data=data,
    )]


# -- hot-path builders ------------------------------------------------------


def _synth_corpus(vocab_size: int, num_pairs: int, seed: int = 0):
    """Zipf-ish pair corpus (the bench.py recipe, inlined so the package
    does not import the repo-root bench script)."""
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    pairs = rng.choice(vocab_size, size=(num_pairs, 2), p=p).astype(np.int32)
    counts = np.bincount(
        pairs.reshape(-1), minlength=vocab_size
    ).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(vocab_size)], counts), pairs)


def build_sgns(
    dim: int = 32,
    vocab: int = 128,
    batch_pairs: int = 64,
    num_pairs: int = 512,
    mesh: Optional[Tuple[int, int]] = None,
    **cfg_kw,
):
    """(trainer, params, lowered, args_maker) for the SGNS epoch.

    ``mesh=(data, model)`` compiles the sharded program (needs the
    virtual multi-device CPU backend); None runs unsharded.
    """
    import jax

    from gene2vec_tpu.config import MeshConfig, SGNSConfig
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = _synth_corpus(vocab, num_pairs)
    config = SGNSConfig(dim=dim, batch_pairs=batch_pairs, **cfg_kw)
    sharding = None
    if mesh is not None:
        from gene2vec_tpu.parallel.mesh import make_mesh
        from gene2vec_tpu.parallel.sharding import SGNSSharding

        data, model = mesh
        sharding = SGNSSharding(
            make_mesh(MeshConfig(data=data, model=model)),
            vocab_sharded=config.vocab_sharded,
        )
    trainer = SGNSTrainer(corpus, config, sharding=sharding)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    lowered = trainer._epoch_fn.lower(
        params, trainer.pairs, trainer.noise, key
    )

    def args_maker():
        return (trainer.init(), trainer.pairs, trainer.noise,
                jax.random.PRNGKey(1))

    return trainer, params, lowered, args_maker


def build_cbow_hs(
    objective: str = "cbow_hs",
    dim: int = 32,
    vocab: int = 128,
    batch_pairs: int = 64,
    num_pairs: int = 512,
    **cfg_kw,
):
    """(trainer, params, lowered, args_maker) for a CBOW/HS epoch."""
    import dataclasses

    import jax

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.cbow_hs import make_trainer

    corpus = _synth_corpus(vocab, num_pairs)
    config = dataclasses.replace(
        SGNSConfig(dim=dim, batch_pairs=batch_pairs, **cfg_kw),
        objective=objective,
    )
    trainer = make_trainer(corpus, config)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    lowered = trainer._epoch_fn.lower(params, trainer.pairs, key)

    def args_maker():
        return (trainer.init(), trainer.pairs, jax.random.PRNGKey(1))

    return trainer, params, lowered, args_maker


def build_ggipnn(vocab_size: int = 64, batch: int = 16):
    """(trainer, state, lowered, args_maker) for the GGIPNN train step."""
    import jax
    import jax.numpy as jnp

    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_data import PairTextVocab
    from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer

    config = GGIPNNConfig(embedding_dim=16, batch_size=batch)
    vocab = PairTextVocab().fit(
        [f"G{i} G{(i + 1) % vocab_size}" for i in range(vocab_size)]
    )
    trainer = GGIPNNTrainer(config, vocab)
    params, opt_state = trainer.init_state()

    rng = np.random.RandomState(0)

    def make_batch():
        x = jnp.asarray(
            rng.randint(0, vocab_size, (batch, 2)), jnp.int32
        )
        y = jnp.asarray(np.eye(2, dtype=np.float32)[
            rng.randint(0, 2, (batch,))
        ])
        return x, y

    x, y = make_batch()
    key = jax.random.PRNGKey(0)
    lowered = type(trainer).train_step.lower(
        trainer, params, opt_state, x, y, key
    )

    def args_maker():
        p, o = trainer.init_state()
        bx, by = make_batch()
        return (p, o, bx, by, jax.random.PRNGKey(1))

    return trainer, (params, opt_state), lowered, args_maker


def build_serve(
    dim: int = 16,
    vocab: int = 128,
    max_batch: int = 8,
    k: int = 4,
    mesh: Optional[Tuple[int, int]] = None,
):
    """(engine, unit, lowered, args_maker) for the serve top-k kernel.

    ``mesh=(data, model)`` row-shards the unit matrix over the model
    axis (``parallel/sharding.py:row_sharding``) — the layout whose
    per-query collective bytes the ``serve`` budget section gates."""
    import jax.numpy as jnp

    from gene2vec_tpu.serve.engine import SimilarityEngine
    from gene2vec_tpu.serve.registry import l2_normalize

    rng = np.random.RandomState(0)
    unit_np = l2_normalize(rng.randn(vocab, dim).astype(np.float32))
    valid = None
    mesh_obj = None
    if mesh is not None:
        import jax

        from gene2vec_tpu.config import MeshConfig
        from gene2vec_tpu.parallel.mesh import make_mesh
        from gene2vec_tpu.parallel.sharding import row_sharding
        from gene2vec_tpu.serve.registry import dim0_shards

        data, model = mesh
        mesh_obj = make_mesh(MeshConfig(data=data, model=model))
        sharding = row_sharding(mesh_obj)
        pad = (-vocab) % dim0_shards(sharding)
        if pad:
            unit_np = np.concatenate(
                [unit_np, np.zeros((pad, dim), np.float32)]
            )
            valid = vocab
        unit = jax.device_put(jnp.asarray(unit_np), sharding)
    else:
        unit = jnp.asarray(unit_np)
    engine = SimilarityEngine(max_batch=max_batch, mesh=mesh_obj)
    queries = jnp.asarray(rng.randn(max_batch, dim).astype(np.float32))
    lowered = engine._topk_fn.lower(unit, queries, k, valid)

    def args_maker():
        q = jnp.asarray(rng.randn(max_batch, dim).astype(np.float32))
        return (unit, q, k, valid)

    return engine, unit, lowered, args_maker


def serve_bucket_findings(
    dim: int = 16, vocab: int = 128, max_batch: int = 8, k: int = 4
) -> List[Finding]:
    """Jit-cache stability ACROSS the engine's bucketed batch shapes,
    PER INDEX MODE (exact + quant + ivf): one warm cycle over every
    bucket compiles each once; a second cycle must not grow any mode's
    cache (the padded-shape contract that keeps production request
    mixes from recompiling).  The quant/IVF kernels run with a small
    serve/ann.py index built over the same unit matrix, so the exact
    entry points ``cli.serve --index`` would bind are the ones
    compiled."""
    import numpy as _np

    from gene2vec_tpu.serve.ann import build_index
    from gene2vec_tpu.serve.registry import l2_normalize

    engine, unit, _, _ = build_serve(
        dim=dim, vocab=vocab, max_batch=max_batch, k=k
    )
    rng = _np.random.RandomState(1)
    unit_np = l2_normalize(_np.asarray(unit))
    indexes = {
        "quant": build_index(unit_np, "quant"),
        "ivf": build_index(unit_np, "ivf", clusters=max(4, vocab // 16)),
    }

    def cycle():
        for n in engine.buckets:
            q = rng.randn(n, dim).astype(_np.float32)
            engine.top_k(unit, q, k)
            for index in indexes.values():
                engine.top_k_ann(index, unit, q, k)

    cycle()
    after_warmup = engine.cache_sizes()
    if all(v is None for v in after_warmup.values()):
        return [Finding(
            pass_id="hlo-cache-stability",
            severity="info",
            path="hlo:serve/buckets",
            message="jit cache size introspection unavailable on this "
                    "jax version; bucket stability not checked",
            data={"checked": False},
        )]
    cycle()
    after = engine.cache_sizes()
    findings: List[Finding] = []
    for mode in after:
        label = f"hlo:serve/buckets/{mode}"
        warm, now = after_warmup.get(mode), after[mode]
        if warm is None or now is None:
            continue
        if now > warm:
            findings.append(Finding(
                pass_id="hlo-cache-stability",
                path=label,
                message=(
                    f"{mode} jit cache grew {warm} -> {now} on a repeat "
                    f"cycle over buckets {engine.buckets} — padded "
                    "request shapes are not hitting the compiled "
                    "executables"
                ),
                data={"checked": True, "mode": mode, "after_warmup": warm,
                      "after": now, "buckets": list(engine.buckets)},
            ))
        else:
            findings.append(Finding(
                pass_id="hlo-cache-stability",
                severity="info",
                path=label,
                message=(
                    f"{mode} stable at {now} cached executable(s) "
                    f"across buckets {engine.buckets}"
                ),
                data={"checked": True, "mode": mode, "cached": now,
                      "buckets": list(engine.buckets)},
            ))
    return findings


def hot_path_findings(
    include_cache_checks: bool = True,
) -> List[Finding]:
    """The default tier-2 sweep over small unsharded instances of all
    four hot paths (SGNS / CBOW-HS / GGIPNN / serve top-k): host
    callbacks + dtype discipline (+ cache stability).  Budgets need the
    full-scale mesh configs and run via :func:`budget_findings`."""
    findings: List[Finding] = []
    specs = [
        ("hlo:sgns", build_sgns, {}),
        ("hlo:cbow_hs", build_cbow_hs, {}),
        ("hlo:ggipnn", build_ggipnn, {}),
        ("hlo:serve", build_serve, {}),
    ]
    for label, builder, kw in specs:
        trainer, _, lowered, args_maker = builder(**kw)
        compiled = lowered.compile()
        text = compiled.as_text()
        findings.extend(host_callback_findings(text, label))
        compute = getattr(
            getattr(trainer, "config", None), "compute_dtype", "float32"
        )
        findings.extend(dtype_findings(text, label, compute_dtype=compute))
        if include_cache_checks:
            fn = (
                getattr(trainer, "_epoch_fn", None)
                or getattr(trainer, "train_step", None)
                or getattr(trainer, "_topk_fn", None)
            )
            if fn is not None:
                findings.extend(
                    cache_stability_findings(fn, args_maker, label)
                )
    if include_cache_checks:
        findings.extend(serve_bucket_findings())
    return findings


def serve_budget_findings(
    lowered,
    label: str,
    budget: Dict,
) -> List[Finding]:
    """Enforce one serve budget entry: per-QUERY collective bytes of the
    compiled row-sharded top-k must stay within
    ``max_bytes_per_query``."""
    from gene2vec_tpu.obs.probes import collective_stats

    stats = collective_stats(lowered)
    if stats is None:
        return [Finding(
            pass_id="hlo-collective-budget",
            path=label,
            message="failed to compile/scan the module for collectives",
        )]
    batch = budget["max_batch"]
    bytes_per_query = stats["total_bytes"] / batch
    data = {
        "bytes_per_query": round(bytes_per_query, 1),
        "max_bytes_per_query": budget["max_bytes_per_query"],
        "reference_bytes_per_query": budget.get(
            "reference_bytes_per_query"
        ),
        "collectives": stats["collectives"],
    }
    if bytes_per_query > budget["max_bytes_per_query"]:
        return [Finding(
            pass_id="hlo-collective-budget",
            path=label,
            message=(
                f"per-query collective bytes {bytes_per_query:,.1f} "
                f"exceed the budget {budget['max_bytes_per_query']:,} "
                f"(reference "
                f"{budget.get('reference_bytes_per_query')}) — the "
                "sharded top-k is gathering more than its candidate "
                "rows"
            ),
            data=data,
        )]
    return [Finding(
        pass_id="hlo-collective-budget",
        severity="info",
        path=label,
        message=(
            f"{bytes_per_query:,.1f} bytes/query within budget "
            f"{budget['max_bytes_per_query']:,}"
        ),
        data=data,
    )]


def budget_findings(
    keys: Optional[List[str]] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Compile each budgeted mesh config at its recorded geometry and
    enforce its per-pair (sgns) / per-query (serve) collective-bytes
    ceiling."""
    budgets = load_budgets(budgets_path)
    findings: List[Finding] = []
    for key, entry in budgets["sgns"].items():
        if keys is not None and key not in keys:
            continue
        _, _, lowered, _ = build_sgns(
            dim=entry["dim"],
            vocab=entry["vocab"],
            batch_pairs=entry["batch_pairs"],
            num_pairs=entry["num_pairs"],
            mesh=tuple(entry["mesh"]),
            vocab_sharded=entry["vocab_sharded"],
            positive_mid=entry.get("positive_mid", 0),
        )
        findings.extend(
            collective_budget_findings(lowered, f"hlo:sgns/{key}", entry)
        )
    for key, entry in budgets.get("serve", {}).items():
        if keys is not None and key not in keys:
            continue
        if "mesh" not in entry:
            # the serve section also carries non-kernel budgets
            # (capacity_rps, gated by passes_serve at the default tier);
            # only entries pinning a mesh geometry compile here
            continue
        _, _, lowered, _ = build_serve(
            dim=entry["dim"],
            vocab=entry["vocab"],
            max_batch=entry["max_batch"],
            k=entry["k"],
            mesh=tuple(entry["mesh"]),
        )
        findings.extend(
            serve_budget_findings(lowered, f"hlo:serve/{key}", entry)
        )
    return findings
