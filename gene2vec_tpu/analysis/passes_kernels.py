"""Kernel-attribution budget gate: the committed BENCH_KERNELS record
vs budgets.json ``kernels.profile``.

jax-free and I/O-only so it rides the DEFAULT ``cli.analyze`` tier
(the passes_perf / passes_obs shape): ``python bench.py
--kernel-profile`` attributes static XLA costs (flops / bytes accessed
/ peak memory + compile seconds) and timed achieved throughput for
every registered compute hot path at the recipe pinned in
``kernels.profile``, measures the profiling overhead with the
alternating-window methodology, and stamps ``BENCH_KERNELS_r*.json``;
this pass re-checks the committed record.

* a MISSING bench is an *info* finding (a fresh checkout must not fail
  lint before its first bench);
* an unreadable record, a record missing a required kernel or a
  required per-kernel field (``require_kernels`` / ``require_fields``
  — a bench that silently drops a kernel or a cost column must gate
  like a regression), a record measured off-recipe, or a profiling
  overhead past ``max_overhead_fraction`` gates hard.

The per-kernel trajectory (utilization, overhead) is additionally
watched by the ``perf.regression`` rules through the ledger's
``kernels`` family (:mod:`gene2vec_tpu.obs.ledger`).

``GENE2VEC_TPU_KERNELS_ROOT`` overrides the artifact root (planted
fixtures and CI sandboxes point it at a staged directory).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

KERNELS_ROOT_ENV = "GENE2VEC_TPU_KERNELS_ROOT"
BENCH_KERNELS_NAME = "BENCH_KERNELS_r18.json"

_PASS = "kernels-attribution-budget"

#: recipe keys the budget pins — geometry AND window shape must match
#: the committed record, or a lucky tiny window passes the overhead
#: gate by variance (the passes_obs lesson)
_RECIPE_KEYS = (
    "dim", "vocab", "num_pairs", "batch_pairs", "serve_rows",
    "serve_dim", "serve_batch", "serve_k", "serve_clusters",
    "rounds", "epochs_per_window",
)


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def kernels_root() -> str:
    return os.environ.get(KERNELS_ROOT_ENV) or REPO_ROOT


def _newest_kernels_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_KERNELS_r*`` artifact under ``root`` (highest
    round wins, mtime breaks ties) — round convention, like the
    ledger, not one filename pinned forever."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        if ledger.match_family(name) and name.startswith("BENCH_KERNELS"):
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime, path))
    if not candidates:
        return None
    return max(candidates)[2]


def kernels_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Check the newest committed BENCH_KERNELS record against the
    ``kernels.profile`` budget."""
    budget = load_budgets(budgets_path).get("kernels", {}).get("profile")
    if not isinstance(budget, dict):
        return []
    root = root or kernels_root()
    path = _newest_kernels_bench(root) or os.path.join(
        root, BENCH_KERNELS_NAME
    )
    label = os.path.basename(path)
    if not os.path.exists(path):
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no kernel-attribution bench recorded yet ({label} "
                "missing); run `python bench.py --kernel-profile` (it "
                "reads the pinned recipe from budgets.json 'kernels') "
                "to stamp one"
            ),
        )]
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable kernel-attribution bench: {e}",
        )]

    kernels = bench.get("kernels")
    kernels = kernels if isinstance(kernels, dict) else {}
    overhead = bench.get("overhead")
    overhead = overhead if isinstance(overhead, dict) else {}
    recipe = bench.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    ceiling = float(budget.get("max_overhead_fraction", 0.02))
    regression = _get(overhead, "regression_frac")
    require_kernels = [
        str(k) for k in budget.get("require_kernels", [])
    ]
    require_fields = [
        str(k) for k in budget.get("require_fields", [])
    ]
    data: Dict = {
        "kernels": sorted(kernels),
        "regression_frac": regression,
        "max_overhead_fraction": ceiling,
        "recipe": recipe,
    }
    problems: List[str] = []
    # the artifact CONTRACT: every required kernel present with every
    # required field — a bench that drops serve_topk_ivf or stops
    # recording utilization must gate, not silently shrink coverage
    for name in require_kernels:
        rec = kernels.get(name)
        if not isinstance(rec, dict):
            problems.append(f"required kernel {name!r} missing")
            continue
        for field in require_fields:
            if _get(rec, field) is None:
                problems.append(
                    f"kernel {name!r} missing required field {field!r}"
                )
    if regression is None:
        problems.append(
            "overhead.regression_frac missing from the bench record"
        )
    elif regression > ceiling:
        problems.append(
            f"profiler-on vs profiler-off throughput regression "
            f"{regression:.4f} > budget {ceiling} (kernel attribution "
            "grew a steady-state cost — it must stay warm-time/"
            "epoch-level, never per-batch)"
        )
    for key in _RECIPE_KEYS:
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(recipe, key)
        data[f"budget_{key}"] = pinned
        if measured is None:
            problems.append(f"recipe.{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"bench measured with {key}={measured:g} but the budget "
                f"pins {key}={pinned:g} — re-run `python bench.py "
                "--kernel-profile`"
            )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "kernel-attribution record violates the kernels budget: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"{len(kernels)} kernels attributed "
            f"({', '.join(sorted(require_kernels))} required); "
            f"profiling overhead {regression:+.4f} within budget "
            f"(<= {ceiling})"
        ),
        data=data,
    )]
