"""Elastic-fleet budget gate: BENCH_AUTOSCALE vs budgets.json
``autoscale``.

The chaos drill's autoscale phase (``scripts/chaos_drill.py``, phase
``autoscale``) exercises the elastic fleet end to end: a load ramp must
trigger a scale-up decision within the budgeted number of scrape ticks
(``scale_up_detection_ticks``), the hysteresis scale-down must drain
the victim replica with ZERO dropped/wrong/mixed-iteration answers
under continuous verified load, the post-convergence steady-state
window must record zero further scale actions (no flapping), and an
abusive tenant flooding its quota must leave a victim tenant's
availability at or above the budget floor.  Results land in
``BENCH_AUTOSCALE_r*.json``; this pass re-checks the NEWEST committed
record against the ``autoscale`` section of ``budgets.json`` on every
``cli.analyze`` run — elasticity that quietly slows down, starts
dropping drained requests, or stops isolating tenants fails the
analyzer exactly like a collective-bytes regression does.

Deliberately jax-free and I/O-only (two small JSON reads): it rides
the DEFAULT tier.  A missing bench file is an *info* finding (a fresh
checkout must not fail lint before its first drill); a record that
exists and violates — or omits — a budgeted quantity, or was measured
off the pinned recipe, gates hard (the passes_obs recipe-pinning
lesson).  ``GENE2VEC_TPU_AUTOSCALE_ROOT`` overrides the artifact root
for the planted-violation fixtures.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

AUTOSCALE_ROOT_ENV = "GENE2VEC_TPU_AUTOSCALE_ROOT"
BENCH_AUTOSCALE_NAME = "BENCH_AUTOSCALE_r14.json"

_PASS = "autoscale-elasticity-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_autoscale_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_AUTOSCALE_r*`` under ``root`` (highest round
    wins, mtime breaks ties) — a violating r15 must beat a stale clean
    r14, the round convention every bench family follows."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched and matched[0] == "autoscale":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime, path))
    if not candidates:
        return None
    return max(candidates)[2]


def autoscale_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the recorded elasticity drill against the budget."""
    budgets: Dict = load_budgets(budgets_path).get("autoscale", {})
    if not budgets:
        return []
    root = root or os.environ.get(AUTOSCALE_ROOT_ENV) or REPO_ROOT
    path = _newest_autoscale_bench(root) or os.path.join(
        root, BENCH_AUTOSCALE_NAME
    )
    label = os.path.basename(path)
    if not os.path.exists(path):
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no elasticity bench recorded yet ({label} missing); "
                "run `python scripts/chaos_drill.py --only autoscale "
                f"--autoscale-out {label}` to stamp one"
            ),
        )]
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable elasticity bench: {e}",
        )]

    findings: List[Finding] = []
    for name, budget in budgets.items():
        if name.startswith("_"):
            continue
        section = bench.get("autoscale")
        if not isinstance(section, dict):
            findings.append(Finding(
                pass_id=_PASS,
                path=label,
                message=(
                    f"{label} has no 'autoscale' results section to "
                    f"check against budget {name!r}"
                ),
            ))
            continue
        findings.extend(_check_one(name, budget, section, label))
    return findings


def _check_one(
    name: str, budget: Dict, section: Dict, label: str
) -> List[Finding]:
    data: Dict = {"budget": name}
    problems: List[str] = []

    # every budgeted quantity must be PRESENT: a record missing a field
    # must gate like a violation, or dropping the key becomes the way
    # to pass (the passes_fleet/passes_alerts lesson)
    def bounded(key: str, bound_key: str, *, upper: bool,
                what: str) -> None:
        bound = _get(budget, bound_key)
        if bound is None:
            return
        measured = _get(section, key)
        data[key] = measured
        data[bound_key] = bound
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif upper and measured > bound:
            problems.append(
                f"{key} {measured:g} > budget {bound:g} ({what})"
            )
        elif not upper and measured < bound:
            problems.append(
                f"{key} {measured:g} < budget {bound:g} ({what})"
            )

    bounded(
        "scale_up_detection_ticks", "max_scale_up_detection_ticks",
        upper=True,
        what="the scaler noticed the ramp too slowly",
    )
    bounded(
        "dropped_answers", "max_dropped_answers", upper=True,
        what="the zero-drop drain dropped requests",
    )
    bounded(
        "wrong_answers", "max_wrong_answers", upper=True,
        what="scale actions produced wrong answers",
    )
    bounded(
        "mixed_iteration_answers", "max_mixed_iteration_answers",
        upper=True,
        what="scale actions mixed model iterations",
    )
    bounded(
        "steady_state_scale_actions", "max_steady_state_scale_actions",
        upper=True,
        what="the fleet flapped after convergence",
    )
    bounded(
        "victim_tenant_availability", "min_victim_availability",
        upper=False,
        what="an abusive tenant starved the victim tenant",
    )
    # the budget pins the drill RECIPE — a no-ramp, no-tenant run must
    # not pass an elasticity gate by construction
    for key in ("min_replicas", "max_replicas", "scrape_interval_s"):
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(section, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"drill ran with {key}={measured:g} but the budget pins "
                f"{key}={pinned:g} — re-run with the budgeted recipe"
            )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                f"elasticity record violates budget {name!r}: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"elasticity within budget {name!r}: scale-up detected in "
            f"{data.get('scale_up_detection_ticks')} tick(s), zero "
            "drops/wrong/mixed during scale-down, "
            f"{data.get('steady_state_scale_actions')} steady-state "
            "action(s), victim tenant availability "
            f"{data.get('victim_tenant_availability')}"
        ),
        data=data,
    )]
