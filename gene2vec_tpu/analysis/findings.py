"""Machine-readable findings for the graftcheck analysis suite.

One schema serves every tier — AST lint passes, jaxpr/HLO invariant
checks, sanitizer parity runs — so ``python -m gene2vec_tpu.cli.analyze
--json`` and ``scripts/run_static_analysis.sh`` emit a single artifact
that CI (or a human) can diff across rounds.  The schema is documented
in docs/STATIC_ANALYSIS.md; bump :data:`SCHEMA` on any shape change.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional

SCHEMA = "gene2vec-tpu/findings/v1"

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation (or informational fact) produced by a pass.

    ``path``/``line``/``col`` locate source findings; HLO/runtime
    findings use ``path`` for a logical label (e.g. ``hlo:sgns/cpu8``)
    and line 0.  ``data`` carries pass-specific structured detail
    (budget numbers, dtype census, ...) and must stay JSON-serializable.
    """

    pass_id: str
    message: str
    path: str = ""
    line: int = 0
    col: int = 0
    severity: str = "error"
    snippet: str = ""
    data: Optional[Dict] = None

    def to_dict(self) -> Dict:
        d = {
            "pass": self.pass_id,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.snippet:
            d["snippet"] = self.snippet
        if self.data is not None:
            d["data"] = self.data
        return d

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        head = f"{loc}: [{self.pass_id}] {self.message}"
        return head + (f"\n    {self.snippet}" if self.snippet else "")


def gating(findings: Iterable[Finding]) -> List[Finding]:
    """The subset that should fail a build (``info`` never gates)."""
    return [f for f in findings if f.severity in ("error", "warning")]


def to_report(findings: Iterable[Finding], meta: Optional[Dict] = None) -> Dict:
    """The findings JSON document (schema + findings + summary)."""
    fs = sorted(
        findings, key=lambda f: (f.path, f.line, f.col, f.pass_id)
    )
    by_pass: Dict[str, int] = {}
    for f in fs:
        by_pass[f.pass_id] = by_pass.get(f.pass_id, 0) + 1
    doc = {
        "schema": SCHEMA,
        "findings": [f.to_dict() for f in fs],
        "summary": {
            "total": len(fs),
            "gating": len(gating(fs)),
            "by_pass": by_pass,
        },
    }
    if meta:
        doc["meta"] = meta
    return doc


def dumps(findings: Iterable[Finding], meta: Optional[Dict] = None) -> str:
    return json.dumps(to_report(findings, meta), indent=2, sort_keys=False)
