"""Pass orchestration: file discovery, pass selection, findings.

The runner is deliberately jax-free so tier-1 lint stays cheap; the
tier-2 jaxpr/HLO checks live in :mod:`gene2vec_tpu.analysis.passes_hlo`
and import jax lazily.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

from gene2vec_tpu.analysis.astpass import ModuleSource, iter_py_files
from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_ast import ALL_PASSES, Pass

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_PRAGMA = re.compile(r"#\s*graftcheck:\s*disable=([\w,\-]+)")


def suppressed(mod: ModuleSource, f: Finding) -> bool:
    """Inline escape hatch for heuristic false positives: a finding whose
    anchor line carries ``# graftcheck: disable=<pass-id>`` is dropped.
    This is the sanctioned route when a name-heuristic pass (e.g.
    missing-donate) misfires on legitimate code — silence the one site,
    never weaken the pass or the repo-wide zero-findings gate.  Every
    entry point that runs passes directly (the shim included) must route
    results through this filter so the pragma means the same thing
    everywhere."""
    m = _PRAGMA.search(mod.line(f.line))
    return bool(m) and f.pass_id in m.group(1).split(",")


def pass_ids() -> List[str]:
    return [p.id for p in ALL_PASSES]


def select_passes(
    select: Optional[Iterable[str]] = None,
    skip: Optional[Iterable[str]] = None,
) -> List[Pass]:
    known = {p.id for p in ALL_PASSES}
    for name in list(select or []) + list(skip or []):
        if name not in known:
            raise ValueError(
                f"unknown pass {name!r}; known: {sorted(known)}"
            )
    passes = list(ALL_PASSES)
    if select:
        passes = [p for p in passes if p.id in set(select)]
    if skip:
        passes = [p for p in passes if p.id not in set(skip)]
    return passes


def default_roots(repo_root: str = REPO_ROOT) -> Dict[str, str]:
    """Logical root name → directory, skipping roots absent from this
    checkout (experiments/ is not shipped in a wheel)."""
    roots = {
        "package": os.path.join(repo_root, "gene2vec_tpu"),
        "experiments": os.path.join(repo_root, "experiments"),
    }
    return {k: v for k, v in roots.items() if os.path.isdir(v)}


def run_ast_passes(
    repo_root: str = REPO_ROOT,
    select: Optional[Iterable[str]] = None,
    skip: Optional[Iterable[str]] = None,
    files: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the AST passes over the repo (or an explicit ``files`` list,
    which every selected pass sees regardless of its default roots —
    the fixture-test entry point)."""
    passes = select_passes(select, skip)
    findings: List[Finding] = []

    if files is not None:
        work = [(os.path.abspath(f), passes) for f in files]
    else:
        roots = default_roots(repo_root)
        work = []
        for root_name, root_dir in roots.items():
            root_passes = [p for p in passes if root_name in p.roots]
            if not root_passes:
                continue
            for path in iter_py_files(root_dir):
                work.append((path, root_passes))

    for path, file_passes in work:
        rel = os.path.relpath(path, repo_root)
        try:
            mod = ModuleSource.load(path, repo_root)
        except OSError as e:
            findings.append(Finding(
                pass_id="parse", message=f"unreadable: {e}", path=rel,
            ))
            continue
        if mod is None:
            findings.append(Finding(
                pass_id="parse", message="syntax error", path=rel,
            ))
            continue
        for p in file_passes:
            if p.applies(rel):
                findings.extend(
                    f for f in p.run(mod) if not suppressed(mod, f)
                )
    return findings
