"""Dead-budget lint: budgets.json keys and gating passes must anchor.

Two silent-rot failure modes this gates (docs/STATIC_ANALYSIS.md):

* a ``budgets.json`` entry nothing reads — a budget that was renamed or
  whose consumer was deleted keeps "passing" forever.  Every
  ``section.subkey`` must be consumed: some ``.py`` file under
  ``gene2vec_tpu/``, ``scripts/`` or ``tests/`` mentions BOTH the quoted
  section name and the quoted subkey (the access idiom everywhere is
  ``load_budgets().get("serve", {}).get("capacity_rps")`` or
  ``budgets["resilience"]["async_ckpt"]``, so the literals are present
  exactly when the budget is load-bearing);
* a gating pass with no anchor — a pass id registered in the analyzer
  but exercised by no planted-violation fixture and tied to no budget
  can regress to never-fires without any signal.  Every AST and
  concurrency pass id must appear quoted under ``tests/`` (its fixture)
  or in ``budgets.json``.

Both conditions gate as errors in the default ``cli.analyze`` tier,
pass id ``budget-lint``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding

PASS_ID = "budget-lint"

#: sources scanned for budget-key consumption
_CONSUMER_DIRS = ("gene2vec_tpu", "scripts", "tests")


def _iter_sources(root: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for sub in _CONSUMER_DIRS:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in filenames:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, root)
                if rel.endswith(os.path.join("analysis", "budget_lint.py")):
                    continue  # the lint itself never counts as a consumer
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        out[rel] = f.read()
                except OSError:
                    continue
    return out


def _quoted_in(needle: str, text: str) -> bool:
    return f'"{needle}"' in text or f"'{needle}'" in text


def _anchor_line(lines: List[str], section: str, sub: str) -> int:
    """The budgets.json line of ``"sub"`` inside ``"section"`` (best
    effort; 1 when not found)."""
    in_section = False
    for i, text in enumerate(lines, start=1):
        if f'"{section}"' in text:
            in_section = True
            continue
        if in_section and f'"{sub}"' in text:
            return i
    return 1


def budget_lint_findings(repo_root: Optional[str] = None) -> List[Finding]:
    from gene2vec_tpu.analysis.runner import REPO_ROOT, pass_ids

    root = repo_root or REPO_ROOT
    budgets_rel = os.path.join("gene2vec_tpu", "analysis", "budgets.json")
    budgets_path = os.path.join(root, budgets_rel)
    with open(budgets_path, "r", encoding="utf-8") as f:
        raw = f.read()
    budgets = json.loads(raw)
    lines = raw.splitlines()
    sources = _iter_sources(root)

    findings: List[Finding] = []

    # ---- stale budget keys ----------------------------------------------
    for section, entry in sorted(budgets.items()):
        if section.startswith("_"):
            continue
        subkeys = sorted(entry) if isinstance(entry, dict) else [None]
        # `budgets["sgns"].items()` iterates every subkey — that
        # consumes the whole section without quoting the subkey names
        iterated = re.compile(
            r"[\"']" + re.escape(section) + r"[\"'].{0,40}\.items\(\)"
        )
        section_iterated = any(
            iterated.search(text) for text in sources.values()
        )
        for sub in subkeys:
            consumed = section_iterated or any(
                _quoted_in(section, text)
                and (sub is None or _quoted_in(sub, text))
                for text in sources.values()
            )
            if consumed:
                continue
            key = section if sub is None else f"{section}.{sub}"
            findings.append(Finding(
                pass_id=PASS_ID,
                message=(
                    f"budgets.json key '{key}' is consumed by no pass, "
                    "script, or test — a budget nothing reads cannot "
                    "gate; delete the key or restore its consumer"
                ),
                path=budgets_rel,
                line=_anchor_line(lines, section, sub or section),
                snippet="",
                data={"key": key},
            ))

    # ---- unanchored gating passes ---------------------------------------
    from gene2vec_tpu.analysis.passes_concurrency import (
        CONCURRENCY_PASS_IDS,
    )

    test_corpus = "".join(
        text for rel, text in sources.items()
        if rel.split(os.sep, 1)[0] == "tests"
    )
    for pid in list(pass_ids()) + list(CONCURRENCY_PASS_IDS) + [PASS_ID]:
        if _quoted_in(pid, test_corpus) or _quoted_in(pid, raw):
            continue
        findings.append(Finding(
            pass_id=PASS_ID,
            message=(
                f"gating pass '{pid}' has no fixture or budget anchor — "
                "a pass no planted violation exercises can silently "
                "stop firing; add a fixture under tests/ or tie it to "
                "a budgets.json entry"
            ),
            path=budgets_rel,
            line=1,
            snippet="",
            data={"pass": pid},
        ))
    return findings
