"""Concurrency tier: lock discipline, loop blocking, lock order.

Four project-level passes over the :mod:`threadflow` role/lock model
(docs/STATIC_ANALYSIS.md "Concurrency tier").  Unlike the per-module
AST passes these need the cross-module call graph, so they run as one
unit from ``cli.analyze``'s default tier and from the fixture tests via
:func:`concurrency_findings(files=..., select=...)`:

* ``lock-discipline`` (error) — an attribute written from two or more
  thread roles must have a common lock across all its write sites, be
  handed off via a queue instead of written, or be declared with the
  ``# graftcheck: shared=<reason>`` pragma (single-reference hot-swap
  and monotonic-flag idioms).  Declared attrs emit an ``info`` finding
  carrying the justification, so ``--json`` output surfaces every
  suppression's written reason.
* ``loop-thread-blocking`` (error) — generalizes passes_ast's
  ``event-loop-blocking`` from the ``_on_*`` syntactic allowlist to
  everything *reachable* from a loop-thread entry point; findings carry
  the entry → ... → site witness chain.
* ``blocking-while-locked`` (warning) — a blocking call made while
  holding a lock that loop/worker threads also take stalls the serve
  path behind slow I/O.
* ``lock-order`` (error) — cycles in the static lock-acquisition graph
  (nested ``with``-lock scopes, direct or through resolved calls) gate
  with per-edge witness paths.

The ``# graftcheck: disable=<pass-id>`` line pragma works here exactly
as in the AST tier (routed through :func:`runner.suppressed`).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.threadflow import (
    ROLE_LOOP,
    ROLE_WORKER,
    FuncInfo,
    LockId,
    ThreadModel,
    build_model,
)

CONCURRENCY_PASS_IDS = (
    "lock-discipline",
    "loop-thread-blocking",
    "blocking-while-locked",
    "lock-order",
)


def concurrency_findings(
    repo_root: Optional[str] = None,
    files: Optional[List[str]] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the concurrency tier; ``files`` scopes the model to explicit
    modules (the fixture-test entry point), ``select`` to a subset of
    :data:`CONCURRENCY_PASS_IDS`."""
    from gene2vec_tpu.analysis.runner import REPO_ROOT, suppressed

    root = repo_root or REPO_ROOT
    want = set(select) if select is not None else set(CONCURRENCY_PASS_IDS)
    unknown = want - set(CONCURRENCY_PASS_IDS)
    if unknown:
        raise ValueError(
            f"unknown concurrency pass(es) {sorted(unknown)}; "
            f"known: {list(CONCURRENCY_PASS_IDS)}"
        )
    model = build_model(root, files=files)
    out: List[Finding] = []
    if "lock-discipline" in want:
        out.extend(_lock_discipline(model))
    if "loop-thread-blocking" in want:
        out.extend(_loop_thread_blocking(model))
    if "blocking-while-locked" in want:
        out.extend(_blocking_while_locked(model))
    if "lock-order" in want:
        out.extend(_lock_order(model))
    kept = []
    for f in out:
        mod = model.modules.get(f.path)
        if mod is not None and suppressed(mod, f):
            continue
        kept.append(f)
    return kept


def _held_at(site) -> FrozenSet[LockId]:
    return site.held | (site.func.inherited or frozenset())


def _lock_discipline(model: ThreadModel) -> List[Finding]:
    by_attr: Dict[Tuple[str, Optional[str], str], List] = {}
    for fn in model.funcs.values():
        for w in fn.writes:
            by_attr.setdefault(w.attr_id, []).append(w)

    out: List[Finding] = []
    for attr_id, sites in sorted(
        by_attr.items(), key=lambda kv: (kv[0][0], kv[0][2])
    ):
        rel, cls, attr = attr_id
        roles: Set[str] = set()
        for w in sites:
            roles |= model.roles_of(w.func)
        if len(roles) < 2:
            continue  # single-role attr: no cross-thread write hazard
        common = None
        for w in sites:
            held = _held_at(w)
            common = held if common is None else (common & held)
        label = f"{cls}.{attr}" if cls else attr
        declared = model.shared_declared.get(attr_id)
        anchor = min(sites, key=lambda w: (w.line,))
        detail = {
            "attr": label,
            "roles": sorted(roles),
            "writes": [
                {
                    "path": w.func.mod.rel,
                    "line": w.line,
                    "func": w.func.qual,
                    "roles": sorted(model.roles_of(w.func)),
                    "locks": sorted(_held_at(w)),
                }
                for w in sorted(sites, key=lambda w: (w.func.mod.rel, w.line))
            ],
        }
        if common:
            continue  # every write path shares a lock: disciplined
        if declared is not None:
            detail["justification"] = declared
            out.append(Finding(
                pass_id="lock-discipline",
                severity="info",
                message=(
                    f"shared attr {label} declared via pragma: {declared}"
                ),
                path=rel, line=anchor.line,
                snippet=anchor.func.mod.line(anchor.line),
                data=detail,
            ))
            continue
        out.append(Finding(
            pass_id="lock-discipline",
            message=(
                f"attr {label} written from roles "
                f"{{{', '.join(sorted(roles))}}} with no common lock — "
                "add a lock, hand off via a queue, or declare "
                "`# graftcheck: shared=<reason>`"
            ),
            path=rel, line=anchor.line,
            snippet=anchor.func.mod.line(anchor.line),
            data=detail,
        ))
    return out


def _loop_thread_blocking(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for fn in sorted(
        model.funcs.values(), key=lambda f: (f.mod.rel, f.node.lineno)
    ):
        if ROLE_LOOP not in fn.roles:
            continue
        chain = model.role_chain(fn, ROLE_LOOP)
        for b in fn.blocking:
            key = (fn.mod.rel, b.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                pass_id="loop-thread-blocking",
                message=(
                    f"{b.desc} reachable from a loop-thread entry "
                    f"({' -> '.join(chain)}) — the event loop must "
                    "never block"
                ),
                path=fn.mod.rel, line=b.line,
                snippet=fn.mod.line(b.line),
                data={"call": b.desc, "witness": chain},
            ))
    return out


def _blocking_while_locked(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    for fn in sorted(
        model.funcs.values(), key=lambda f: (f.mod.rel, f.node.lineno)
    ):
        for b in fn.blocking:
            held = _held_at(b)
            if not held:
                continue
            serve_locks = sorted(
                lock for lock in held
                if model.lock_roles.get(lock, set()) & {ROLE_LOOP, ROLE_WORKER}
            )
            if not serve_locks:
                continue
            out.append(Finding(
                pass_id="blocking-while-locked",
                severity="warning",
                message=(
                    f"{b.desc} while holding {', '.join(serve_locks)} — "
                    "serve threads contending on this lock stall behind "
                    "the blocking call"
                ),
                path=fn.mod.rel, line=b.line,
                snippet=fn.mod.line(b.line),
                data={
                    "call": b.desc, "locks": serve_locks,
                    "func": fn.qual,
                },
            ))
    return out


def _lock_order(model: ThreadModel) -> List[Finding]:
    # reachable acquisitions per function, with one witness path each
    acq_star: Dict[str, Dict[LockId, Tuple[str, ...]]] = {
        f.key: {} for f in model.funcs.values()
    }
    for f in model.funcs.values():
        for lock, line, _held in f.acquires:
            acq_star[f.key].setdefault(lock, (f"{f.qual} ({f.mod.rel}:{line})",))
    for _ in range(24):
        changed = False
        for f in model.funcs.values():
            mine = acq_star[f.key]
            for site in f.calls:
                for lock, path in acq_star[site.callee.key].items():
                    if lock not in mine and len(path) < 8:
                        mine[lock] = (
                            f"{f.qual} ({f.mod.rel}:{site.line})",
                        ) + path
                        changed = True
        if not changed:
            break

    # edges: holding L0, acquire L1 (directly or via a call)
    edges: Dict[LockId, Dict[LockId, Tuple[str, int, str]]] = {}

    def add_edge(l0: LockId, l1: LockId, rel: str, line: int, why: str):
        if l0 == l1:
            return  # RLock re-entry / self-edge: not an ordering edge
        edges.setdefault(l0, {}).setdefault(l1, (rel, line, why))

    for f in model.funcs.values():
        inherited = f.inherited or frozenset()
        for lock, line, held_before in f.acquires:
            for l0 in held_before | inherited:
                add_edge(
                    l0, lock, f.mod.rel, line,
                    f"{f.qual} ({f.mod.rel}:{line}) acquires {lock} "
                    f"while holding {l0}",
                )
        for site in f.calls:
            held = site.held | inherited
            if not held:
                continue
            for lock, path in acq_star[site.callee.key].items():
                for l0 in held:
                    add_edge(
                        l0, lock, f.mod.rel, site.line,
                        f"{f.qual} ({f.mod.rel}:{site.line}) holding "
                        f"{l0} -> " + " -> ".join(path),
                    )

    # cycle detection over the lock digraph
    out: List[Finding] = []
    seen_cycles: Set[Tuple[LockId, ...]] = set()
    for start in sorted(edges):
        stack = [(start, (start,))]
        visited: Set[LockId] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, {})):
                if nxt == start:
                    cycle = path
                    # canonical rotation so each cycle reports once
                    i = cycle.index(min(cycle))
                    canon = cycle[i:] + cycle[:i]
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    witness = []
                    ring = list(cycle) + [cycle[0]]
                    for a, b in zip(ring, ring[1:]):
                        witness.append(edges[a][b][2])
                    rel, line, _ = edges[cycle[-1]][start]
                    out.append(Finding(
                        pass_id="lock-order",
                        message=(
                            "lock-acquisition cycle "
                            + " -> ".join(ring)
                            + " (potential deadlock)"
                        ),
                        path=rel, line=line,
                        snippet=model.modules[rel].line(line)
                        if rel in model.modules else "",
                        data={"cycle": list(canon), "witness": witness},
                    ))
                elif nxt not in path and nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + (nxt,)))
    return out
