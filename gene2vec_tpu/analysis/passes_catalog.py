"""Catalog-plane budget gate: BENCH_CATALOG vs budgets.json
``catalog``.

``python scripts/chaos_drill.py --only catalog --catalog-out
BENCH_CATALOG_r*.json`` stamps the multi-model serving plane's
isolation record — a two-model catalog fleet hot-swaps its default
model under closed-loop verified load on BOTH models, then ramps the
second model and proves only that model's pool scales, with every
answer classified post-hoc for wrong / mixed-iteration / cross-model
content.  This pass re-checks the NEWEST committed record against the
``isolation`` entry of the ``catalog`` budgets section every
``cli.analyze`` run, so a catalog plane that quietly starts answering
from the wrong model, bleeding swaps across pools, or scaling the
cold pool fails the analyzer exactly like a collective-bytes
regression does.

Rules (the passes_batch / passes_loop shape — jax-free, I/O-only, so
it rides the DEFAULT tier):

* no ``BENCH_CATALOG_r*`` artifact at all → *info* (a fresh checkout
  must not fail lint before its first drill);
* the budget pins the **measurement recipe** (model count, replicas
  per pool, autoscale ceiling, vocab, both dims, k): a record
  measured off-recipe gates hard — isolation at one model must not
  pass a gate whose contract is two;
* ``max_wrong_answers`` / ``max_mixed_answers`` /
  ``max_cross_model_answers`` are hard counts (all pinned to 0): a
  single answer from the wrong model, the wrong iteration, or a
  straddled swap gates; a missing budgeted quantity gates like a
  violation — dropping the key must never be the way to pass;
* verified availability over both load windows must hold
  ``min_availability``;
* ``require_swap`` / ``require_scale_up``: the record must actually
  contain the hot-swap and the per-model scale-up it claims to have
  survived, and the scale-up's end state must show the cold pool
  still at its floor (``cold_pool_final == 1``) — pool isolation is
  the whole point;
* the scale-up decision must land within
  ``max_scale_up_detection_ticks`` scrape ticks;
* a drill that stamped ``passed: false`` gates on its own verdict.

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (shared with
``passes_perf``/``passes_batch`` so staged fixture dirs work
uniformly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.passes_perf import perf_root

_PASS = "catalog-isolation-budget"

#: budget recipe key -> bench record recipe key (identical names; the
#: indirection exists so the pinning loop is data, not code)
_RECIPE_KEYS = (
    "models",
    "replicas_per_model",
    "max_replicas",
    "vocab",
    "dim_default",
    "dim_second",
    "k",
)

#: verified-answer count key -> budget ceiling key
_COUNT_CEILINGS = (
    ("wrong", "max_wrong_answers"),
    ("mixed", "max_mixed_answers"),
    ("cross_model", "max_cross_model_answers"),
)


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    return float(v) if isinstance(v, (int, float)) else None


def _newest_catalog_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_CATALOG_*`` artifact under ``root`` (highest
    round wins, mtime breaks ties)."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched is not None and matched[0] == "catalog":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime,
                               path))
    if not candidates:
        return None
    return max(candidates)[2]


def catalog_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the newest committed catalog drill against
    ``catalog.isolation``."""
    budget = load_budgets(budgets_path).get("catalog", {}).get(
        "isolation")
    if not isinstance(budget, dict):
        return []
    root = root or perf_root()
    path = _newest_catalog_bench(root)
    if path is None:
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path="BENCH_CATALOG",
            message=(
                "no catalog drill recorded yet (BENCH_CATALOG_r*.json "
                "missing); run `python scripts/chaos_drill.py --only "
                "catalog --catalog-out BENCH_CATALOG_rNN.json` (it "
                "reads the pinned recipe from budgets.json 'catalog') "
                "to stamp one"
            ),
        )]
    label = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable catalog drill record: {e}",
        )]

    problems: List[str] = []
    data: Dict = {"budget": "catalog.isolation"}
    section = bench.get("catalog")
    section = section if isinstance(section, dict) else {}

    recipe = section.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    for key in _RECIPE_KEYS:
        pinned = _get(budget, key)
        if pinned is None:
            continue
        measured = _get(recipe, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(
                f"recipe.{key} missing from the drill record"
            )
        elif measured != pinned:
            problems.append(
                f"drill measured with {key}={measured:g} but the "
                f"budget pins {key}={pinned:g} — re-run the catalog "
                "drill"
            )

    verified = section.get("verified")
    verified = verified if isinstance(verified, dict) else {}
    for count_key, ceiling_key in _COUNT_CEILINGS:
        ceiling = _get(budget, ceiling_key)
        if ceiling is None:
            continue
        count = _get(verified, count_key)
        data[count_key] = count
        if count is None:
            problems.append(
                f"verified.{count_key} missing from the drill record"
            )
        elif count > ceiling:
            problems.append(
                f"verified.{count_key} {count:g} > budget "
                f"{ceiling_key} {ceiling:g} — answers leaked across "
                "the catalog's isolation boundary"
            )
    floor = _get(budget, "min_availability")
    availability = _get(verified, "availability")
    data["availability"] = availability
    if floor is not None:
        if availability is None:
            problems.append(
                "verified.availability missing from the drill record"
            )
        elif availability < floor:
            problems.append(
                f"verified.availability {availability:g} < budget "
                f"{floor:g}"
            )

    swap = section.get("swap")
    swap = swap if isinstance(swap, dict) else {}
    if _get(budget, "require_swap"):
        if _get(swap, "to_iteration") != 2:
            problems.append(
                "swap.to_iteration is not 2 — the record does not "
                "show the default model's hot swap it claims to have "
                "survived"
            )
        data["swap_visible_s"] = _get(swap, "visible_s")

    scale = section.get("scale_up")
    scale = scale if isinstance(scale, dict) else {}
    if _get(budget, "require_scale_up"):
        ceiling = _get(budget, "max_replicas")
        hot = _get(scale, "hot_pool_final")
        cold = _get(scale, "cold_pool_final")
        data["hot_pool_final"] = hot
        data["cold_pool_final"] = cold
        if hot is None or (ceiling is not None and hot < ceiling):
            problems.append(
                f"scale_up.hot_pool_final {hot} never reached "
                f"max_replicas {ceiling} — the ramped model's pool "
                "did not scale"
            )
        if cold != 1:
            problems.append(
                f"scale_up.cold_pool_final {cold} != 1 — the ramp on "
                "one model moved the OTHER model's pool; isolation is "
                "broken"
            )
    max_ticks = _get(budget, "max_scale_up_detection_ticks")
    ticks = _get(scale, "detection_ticks")
    data["detection_ticks"] = ticks
    if max_ticks is not None:
        if ticks is None:
            problems.append(
                "scale_up.detection_ticks missing from the drill "
                "record"
            )
        elif ticks > max_ticks:
            problems.append(
                f"scale_up.detection_ticks {ticks:g} > budget "
                f"{max_ticks:g} — the per-model scaler is slow to see "
                "a single hot pool"
            )

    if bench.get("passed") is False:
        problems.append("the drill itself stamped passed=false")

    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "catalog drill record violates budget "
                "'catalog.isolation': " + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"catalog isolation held: availability "
            f"{data.get('availability')}, 0 wrong/mixed/cross-model "
            f"answers, swap visible in {data.get('swap_visible_s')} s, "
            f"scale-up decided in {data.get('detection_ticks')} ticks "
            f"with the cold pool untouched, within budget "
            "'catalog.isolation'"
        ),
        data=data,
    )]
