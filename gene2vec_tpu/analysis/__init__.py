"""graftcheck: first-party static analysis + sanitizer gating.

Three tiers (docs/STATIC_ANALYSIS.md):

1. AST lint passes for JAX footguns (:mod:`.passes_ast`) — fast, jax-free,
   run inside tier-1 and ``python -m gene2vec_tpu.cli.analyze``;
2. jaxpr/HLO invariant checks (:mod:`.passes_hlo`) — compile the SGNS /
   CBOW-HS / GGIPNN steps on CPU and assert budgets (host callbacks,
   dtype discipline, jit cache stability, collective bytes);
3. sanitizer wiring for ``native/`` (:mod:`.sanitize`) — ASAN/UBSAN/TSAN
   build targets and parity runs.

Findings from every tier share one JSON schema (:mod:`.findings`).
"""

from gene2vec_tpu.analysis.findings import (  # noqa: F401
    SCHEMA,
    Finding,
    dumps,
    gating,
    to_report,
)
from gene2vec_tpu.analysis.passes_ast import ALL_PASSES  # noqa: F401
from gene2vec_tpu.analysis.runner import (  # noqa: F401
    REPO_ROOT,
    pass_ids,
    run_ast_passes,
    select_passes,
)
