"""Serve capacity budget gate: BENCH_SERVE vs budgets.json ``serve``.

``scripts/serve_loadgen.py`` stamps a ``capacity`` section (highest
offered level that sustained its load under the pinned latency/
availability criteria) and — with ``--fleet N`` — a ``fleet_capacity``
section into each ``BENCH_SERVE_r*.json``.  This pass re-checks the
NEWEST committed record against the ``capacity_rps`` entry of the
``serve`` budgets section every ``cli.analyze`` run, so a front-end
capacity regression (a rerun stamping worse numbers, a budget quietly
loosened, a bench re-measured off-recipe) fails the analyzer exactly
like a collective-bytes regression does.

Rules (the passes_fleet / passes_perf shape — jax-free, I/O-only, so
it rides the DEFAULT tier):

* no ``BENCH_SERVE_r*`` artifact at all → *info* (a fresh checkout
  must not fail lint before its first bench);
* newest artifact missing the ``capacity`` section → gating error
  (it was produced by a pre-capacity loadgen — re-run the bench);
* the budget pins the **measurement recipe** (mode, method, k,
  duration, query-gene count, p99/availability criteria): a record
  measured differently gates hard — a lucky 1-second window must not
  pass a capacity gate by variance;
* ``capacity.sustained_rps`` below ``min_capacity_rps`` (and, when
  pinned, ``fleet_capacity.sustained_rps`` below
  ``min_fleet_capacity_rps``, or any fleet-phase wrong/mixed-iteration
  answer) gates hard; a missing budgeted quantity gates like a
  violation — dropping the key must never be the way to pass.

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (shared with
``passes_perf`` so staged fixture dirs work uniformly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.passes_perf import perf_root

_PASS = "serve-capacity-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_serve_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_SERVE_*`` artifact under ``root`` (highest
    round wins, mtime breaks ties) — the gate follows the round
    convention like the ledger does."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched is not None and matched[0] == "serve_loadgen":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime,
                               path))
    if not candidates:
        return None
    return max(candidates)[2]


def serve_capacity_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the newest committed serve bench against ``capacity_rps``."""
    budget = load_budgets(budgets_path).get("serve", {}).get(
        "capacity_rps"
    )
    if not isinstance(budget, dict):
        return []
    root = root or perf_root()
    path = _newest_serve_bench(root)
    if path is None:
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path="BENCH_SERVE",
            message=(
                "no serve bench recorded yet (BENCH_SERVE_r*.json "
                "missing); run `python scripts/serve_loadgen.py "
                "--spawn <export>` per docs/BENCHMARKS.md to stamp one"
            ),
        )]
    label = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable serve bench: {e}",
        )]

    problems: List[str] = []
    data: Dict = {"budget": "capacity_rps"}

    # the budget pins the MEASUREMENT RECIPE — a record measured with a
    # different method/duration/criteria is not comparable
    recipe = budget.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    for key in ("mode", "method"):
        pinned = recipe.get(key)
        if pinned is None:
            continue
        measured = bench.get(key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured != pinned:
            problems.append(
                f"bench measured with {key}={measured!r} but the "
                f"budget pins {key}={pinned!r} — re-run the capacity "
                "bench per docs/BENCHMARKS.md"
            )
    for key, bench_key in (
        ("k", "k"),
        ("duration_s", "duration_s"),
        ("num_query_genes", "num_query_genes"),
    ):
        pinned = _get(recipe, key)
        if pinned is None:
            continue
        measured = _get(bench, bench_key)
        data[f"budget_{key}"] = pinned
        data[bench_key] = measured
        if measured is None:
            problems.append(f"{bench_key} missing from the bench record")
        elif key == "duration_s":
            if measured < pinned:
                problems.append(
                    f"bench windows are {measured:g}s but the budget "
                    f"pins >= {pinned:g}s per level"
                )
        elif measured != pinned:
            problems.append(
                f"bench measured with {bench_key}={measured:g} but the "
                f"budget pins {pinned:g}"
            )

    def check_capacity(section_name: str, min_key: str) -> None:
        floor = _get(budget, min_key)
        if floor is None:
            return
        section = bench.get(section_name)
        if not isinstance(section, dict):
            problems.append(
                f"{section_name} section missing from the bench record "
                "(pre-capacity loadgen output? re-run the bench)"
            )
            return
        sustained = _get(section, "sustained_rps")
        data[f"{section_name}_sustained_rps"] = sustained
        data[min_key] = floor
        if sustained is None:
            problems.append(
                f"{section_name}.sustained_rps missing from the bench "
                "record"
            )
        elif sustained < floor:
            problems.append(
                f"{section_name}.sustained_rps {sustained:g} < budget "
                f"{floor:g} (the front end lost capacity)"
            )
        # the criteria the verdict was computed under must match the
        # budget's — loosening them in the loadgen flags must not pass
        for crit_key, direction in (
            ("p99_budget_ms", "max"), ("min_availability", "min"),
        ):
            pinned = _get(budget, crit_key)
            if pinned is None:
                continue
            measured = _get(section, crit_key)
            if measured is None:
                problems.append(
                    f"{section_name}.{crit_key} missing from the bench "
                    "record"
                )
            elif (direction == "max" and measured > pinned) or (
                direction == "min" and measured < pinned
            ):
                problems.append(
                    f"{section_name} verdict computed under "
                    f"{crit_key}={measured:g}, looser than the "
                    f"budget's {pinned:g}"
                )

    check_capacity("capacity", "min_capacity_rps")
    check_capacity("fleet_capacity", "min_fleet_capacity_rps")

    # fleet-phase answer integrity: zero wrong or mixed-iteration
    # answers across every fleet level (only checked when the budget
    # demands a fleet phase at all)
    if _get(budget, "min_fleet_capacity_rps") is not None:
        fleet_levels = bench.get("fleet_levels")
        if not isinstance(fleet_levels, list) or not fleet_levels:
            problems.append(
                "fleet_levels missing from the bench record (run the "
                "bench with --fleet/--verify)"
            )
        else:
            for row in fleet_levels:
                if not isinstance(row, dict):
                    continue
                for key in ("wrong_answers", "mixed_iteration_answers"):
                    count = _get(row, key)
                    if count is None:
                        problems.append(
                            f"fleet level {row.get('offered_rps')}: "
                            f"{key} missing (run with --verify)"
                        )
                    elif count > 0:
                        problems.append(
                            f"fleet level {row.get('offered_rps')}: "
                            f"{int(count)} {key.replace('_', ' ')} — "
                            "answer integrity is broken in the serve "
                            "path"
                        )

    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "serve capacity record violates budget 'capacity_rps': "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"serve capacity "
            f"{data.get('capacity_sustained_rps'):g} rps (fleet "
            f"{data.get('fleet_capacity_sustained_rps', 0) or 0:g} rps) "
            "within budget 'capacity_rps'"
        ),
        data=data,
    )]
