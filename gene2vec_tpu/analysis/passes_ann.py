"""ANN retrieval budget gate: BENCH_ANN vs budgets.json ``ann``.

``python bench.py --ann`` stamps a ``BENCH_ANN_r*.json`` artifact —
recall@10 vs the exact numpy oracle per index mode on the 1M-row
synthetic table AND the real 24,447-vocab-geometry table, p50/p99 per
mode, and analytic bytes-touched-per-query.  This pass re-checks the
NEWEST committed record against the ``recall`` entry of the ``ann``
budgets section every ``cli.analyze`` run, so an approximate-retrieval
quality collapse (a rerun stamping worse recall, a bench re-measured
off-recipe, the scaling win quietly evaporating) fails the analyzer
exactly like a collective-bytes regression does.

Rules (the passes_serve / passes_perf shape — jax-free, I/O-only, so
it rides the DEFAULT tier):

* no ``BENCH_ANN_r*`` artifact at all → *info* (a fresh checkout must
  not fail lint before its first bench);
* the budget pins the **measurement recipe** (rows, dim, k, query
  count, clusters, nprobe, rescore_mult): a record measured with
  different geometry or looser knobs gates hard — recall at nprobe=256
  must not pass a gate whose serving default is 32;
* IVF **and** quant recall@10 below ``min_recall_at_10`` on either the
  synthetic or the real-geometry table gates hard; a missing budgeted
  quantity gates like a violation — dropping the key must never be the
  way to pass;
* the IVF path must beat exact brute force by ``min_gain_factor`` in
  p99 latency **or** bytes touched per query (bytes are
  host-independent; latency is this container's CPU — either proves
  the scaling story).

``GENE2VEC_TPU_PERF_ROOT`` overrides the artifact root (shared with
``passes_perf``/``passes_serve`` so staged fixture dirs work
uniformly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.passes_perf import perf_root

_PASS = "ann-recall-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _newest_ann_bench(root: str) -> Optional[str]:
    """The newest ``BENCH_ANN_*`` artifact under ``root`` (highest
    round wins, mtime breaks ties) — the gate follows the round
    convention like the ledger does."""
    from gene2vec_tpu.obs import ledger

    candidates = []
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        matched = ledger.match_family(name)
        if matched is not None and matched[0] == "ann":
            path = os.path.join(root, name)
            rnd = ledger.parse_round(name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
            candidates.append((rnd if rnd is not None else -1, mtime,
                               path))
    if not candidates:
        return None
    return max(candidates)[2]


def ann_recall_findings(
    root: Optional[str] = None,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the newest committed ANN bench against ``ann.recall``."""
    budget = load_budgets(budgets_path).get("ann", {}).get("recall")
    if not isinstance(budget, dict):
        return []
    root = root or perf_root()
    path = _newest_ann_bench(root)
    if path is None:
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path="BENCH_ANN",
            message=(
                "no ANN bench recorded yet (BENCH_ANN_r*.json missing); "
                "run `python bench.py --ann` (it reads the pinned "
                "recipe from budgets.json 'ann') to stamp one"
            ),
        )]
    label = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable ANN bench: {e}",
        )]

    problems: List[str] = []
    data: Dict = {"budget": "ann.recall"}

    # the budget pins the MEASUREMENT RECIPE — recall at a different
    # geometry or with looser probe/rescore knobs is not comparable
    recipe = bench.get("recipe")
    recipe = recipe if isinstance(recipe, dict) else {}
    for key in ("rows", "dim", "k", "queries", "clusters", "nprobe",
                "rescore_mult"):
        pinned = _get(budget.get("recipe") or {}, key)
        if pinned is None:
            continue
        measured = _get(recipe, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"recipe.{key} missing from the bench record")
        elif measured != pinned:
            problems.append(
                f"bench measured with {key}={measured:g} but the budget "
                f"pins {key}={pinned:g} — re-run `python bench.py --ann`"
            )

    floor = _get(budget, "min_recall_at_10")
    modes = bench.get("modes")
    modes = modes if isinstance(modes, dict) else {}
    if floor is not None:
        for mode in ("ivf", "quant"):
            section = modes.get(mode)
            recall = (
                _get(section, "recall_at_10")
                if isinstance(section, dict) else None
            )
            data[f"{mode}_recall_at_10"] = recall
            if recall is None:
                problems.append(
                    f"modes.{mode}.recall_at_10 missing from the bench "
                    "record"
                )
            elif recall < floor:
                problems.append(
                    f"modes.{mode}.recall_at_10 {recall:g} < budget "
                    f"{floor:g} (approximate retrieval is losing true "
                    "neighbors)"
                )
        # the real-vocab-geometry table must hold the same floor — a
        # recipe tuned to the synthetic distribution alone could ship
        # a config that loses neighbors at the served geometry
        real = bench.get("real_table")
        real = real if isinstance(real, dict) else {}
        want_rows = _get(budget, "real_table_rows")
        got_rows = _get(real, "rows")
        data["real_table_rows"] = got_rows
        if want_rows is not None and got_rows != want_rows:
            problems.append(
                f"real_table.rows is {got_rows} but the budget pins "
                f"{want_rows:g}"
            )
        for key in ("recall_at_10_ivf", "recall_at_10_quant"):
            recall = _get(real, key)
            data[f"real_{key}"] = recall
            if recall is None:
                problems.append(
                    f"real_table.{key} missing from the bench record"
                )
            elif recall < floor:
                problems.append(
                    f"real_table.{key} {recall:g} < budget {floor:g}"
                )

    # the scaling story: IVF must beat exact by the factor in p99 OR
    # bytes touched per query; both missing gates (dropping the fields
    # must never be the way to pass)
    gain_floor = _get(budget, "min_gain_factor")
    if gain_floor is not None:
        ivf = modes.get("ivf")
        ivf = ivf if isinstance(ivf, dict) else {}
        speedup = _get(ivf, "p99_speedup_vs_exact")
        bytes_factor = _get(ivf, "bytes_reduction_vs_exact")
        data["p99_speedup_vs_exact"] = speedup
        data["bytes_reduction_vs_exact"] = bytes_factor
        data["min_gain_factor"] = gain_floor
        if speedup is None and bytes_factor is None:
            problems.append(
                "modes.ivf carries neither p99_speedup_vs_exact nor "
                "bytes_reduction_vs_exact — the scaling claim is "
                "unmeasured"
            )
        elif max(speedup or 0.0, bytes_factor or 0.0) < gain_floor:
            problems.append(
                f"IVF gain vs exact (p99 {speedup}, bytes "
                f"{bytes_factor}) is below the budget's "
                f"{gain_floor:g}x — the index no longer pays for "
                "itself at 1M rows"
            )

    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                "ANN bench record violates budget 'ann.recall': "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"ANN recall@10 ivf {data.get('ivf_recall_at_10')} / quant "
            f"{data.get('quant_recall_at_10')} (real table "
            f"{data.get('real_recall_at_10_ivf')}), IVF gain "
            f"{max(data.get('p99_speedup_vs_exact') or 0, data.get('bytes_reduction_vs_exact') or 0):g}x "
            "within budget 'ann.recall'"
        ),
        data=data,
    )]
