"""Tracing-overhead budget gate: BENCH_OBS vs budgets.json ``obs``.

``scripts/serve_loadgen.py --trace-overhead`` measures the p50 latency
of requests carrying a sampled ``traceparent`` header against identical
requests with no header, at the offered load pinned in the ``obs``
section of ``budgets.json``, and stamps the comparison into
``BENCH_OBS_r09.json``.  This pass re-checks that committed record on
every ``cli.analyze`` run — tracing that quietly grows past its
overhead ceiling fails the analyzer exactly like a collective-bytes or
fleet-availability regression does.

Deliberately jax-free and I/O-only (two small JSON reads): it runs in
the default tier.  A missing bench file is an *info* finding (a fresh
checkout must not fail lint before its first bench); a record that
exists and violates — or omits — a budgeted quantity gates hard.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.analysis.findings import Finding
from gene2vec_tpu.analysis.passes_hlo import BUDGETS_PATH, load_budgets
from gene2vec_tpu.analysis.runner import REPO_ROOT

BENCH_OBS_PATH = os.path.join(REPO_ROOT, "BENCH_OBS_r09.json")

_PASS = "obs-trace-overhead-budget"


def _get(section: Dict, key: str) -> Optional[float]:
    v = section.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def obs_budget_findings(
    bench_path: str = BENCH_OBS_PATH,
    budgets_path: str = BUDGETS_PATH,
) -> List[Finding]:
    """Gate the recorded trace-overhead results against the budget."""
    budgets: Dict = load_budgets(budgets_path).get("obs", {})
    if not budgets:
        return []
    label = os.path.basename(bench_path)
    if not os.path.exists(bench_path):
        # the hint must reproduce the PINNED recipe exactly — loadgen
        # defaults differ, and _check_one gates on a recipe match, so a
        # hint without these flags would produce a failing record
        b = budgets.get("trace_overhead", {})
        recipe = (
            f"--levels {b.get('rps', 50):g} "
            f"--duration {b.get('duration_s', 4):g} "
            f"--overhead-rounds {b.get('rounds', 5):g}"
        )
        return [Finding(
            pass_id=_PASS,
            severity="info",
            path=label,
            message=(
                f"no tracing-overhead bench recorded yet ({label} "
                "missing); run `python scripts/serve_loadgen.py --spawn "
                f"<export_dir> --trace-overhead {recipe} --output "
                f"{label}` to stamp one"
            ),
        )]
    try:
        with open(bench_path, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=f"unreadable tracing bench: {e}",
        )]

    findings: List[Finding] = []
    for name, budget in budgets.items():
        if name.startswith("_"):
            continue
        section = bench.get("trace_overhead")
        if not isinstance(section, dict):
            findings.append(Finding(
                pass_id=_PASS,
                path=label,
                message=(
                    f"{label} has no 'trace_overhead' section to check "
                    f"against budget {name!r}"
                ),
            ))
            continue
        findings.extend(_check_one(name, budget, section, label))
    return findings


def _check_one(
    name: str, budget: Dict, section: Dict, label: str
) -> List[Finding]:
    p50_untraced = _get(section, "p50_untraced_ms")
    p50_traced = _get(section, "p50_traced_ms")
    regression = _get(section, "regression_frac")
    rps = _get(section, "rps")
    ceiling = float(budget["max_p50_regression_frac"])
    data = {
        "budget": name,
        "p50_untraced_ms": p50_untraced,
        "p50_traced_ms": p50_traced,
        "regression_frac": regression,
        "rps": rps,
        "budget_rps": budget.get("rps"),
        "max_p50_regression_frac": ceiling,
    }
    # every budgeted quantity must be PRESENT: a record missing a field
    # must gate like a violation, or dropping the key becomes the way
    # to pass (the passes_fleet lesson)
    problems: List[str] = []
    for key, value in (
        ("p50_untraced_ms", p50_untraced),
        ("p50_traced_ms", p50_traced),
        ("regression_frac", regression),
        ("rps", rps),
    ):
        if value is None:
            problems.append(f"{key} missing from the bench record")
    # the budget pins the MEASUREMENT RECIPE, not just the load level:
    # a one-tiny-window record on this high-variance host would pass a
    # 2% gate by luck, so duration/rounds must match the pinned values
    for key in ("rps", "duration_s", "rounds"):
        pinned = budget.get(key)
        if pinned is None:
            continue
        measured = _get(section, key)
        data[f"budget_{key}"] = pinned
        data[key] = measured
        if measured is None:
            problems.append(f"{key} missing from the bench record")
        elif float(pinned) != measured:
            problems.append(
                f"bench measured with {key}={measured:g} but the "
                f"budget pins {key}={pinned:g} — re-run with the "
                "budgeted recipe"
            )
    if regression is not None and regression > ceiling:
        problems.append(
            f"traced-vs-untraced p50 regression {regression:.4f} > "
            f"budget {ceiling} (tracing overhead grew past its "
            "ceiling)"
        )
    if problems:
        return [Finding(
            pass_id=_PASS,
            path=label,
            message=(
                f"tracing-overhead record violates budget {name!r}: "
                + "; ".join(problems)
            ),
            data=data,
        )]
    return [Finding(
        pass_id=_PASS,
        severity="info",
        path=label,
        message=(
            f"traced-vs-untraced p50 regression {regression:+.4f} at "
            f"{rps:g} rps within budget {name!r} (<= {ceiling})"
        ),
        data=data,
    )]
