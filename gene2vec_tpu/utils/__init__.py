from gene2vec_tpu.utils.profiling import StepTimer, trace_context  # noqa: F401
