"""Structured step metrics — CSV always, TensorBoard when available.

The reference logs through gensim INFO prints and TF1 summary writers
(loss/accuracy scalars + grad histograms, ``src/GGIPNN_Classification.py:
130-156``).  Here every trainer can emit one row per iteration/step to a
CSV next to its checkpoints, and mirror scalars to tensorboardX when that
package is installed.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Dict, Optional


class MetricsLogger:
    """Append-only metrics log: CSV file + optional TensorBoard scalars."""

    def __init__(self, csv_path: Optional[str], tensorboard_dir: Optional[str] = None):
        self.csv_path = csv_path
        self._fieldnames: Optional[list] = None
        self._tb = None
        if csv_path:
            os.makedirs(os.path.dirname(os.path.abspath(csv_path)), exist_ok=True)
        if tensorboard_dir:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except ImportError:
                pass  # CSV remains the source of truth

    def log(self, step: int, metrics: Dict[str, float]) -> None:
        row = {"step": int(step), "time": time.time(), **metrics}
        if self.csv_path:
            new_fields = sorted(row)
            if self._fieldnames is None:
                exists = os.path.exists(self.csv_path)
                if exists:
                    with open(self.csv_path, "r", encoding="utf-8") as f:
                        header = f.readline().strip()
                    self._fieldnames = header.split(",") if header else new_fields
                else:
                    self._fieldnames = new_fields
                    with open(self.csv_path, "w", encoding="utf-8", newline="") as f:
                        csv.DictWriter(f, self._fieldnames).writeheader()
            new_keys = sorted(set(row) - set(self._fieldnames))
            if new_keys:
                # a metric key appeared after the header froze (e.g. a
                # trainer starts reporting stalls mid-run): rewrite the
                # CSV with the widened header, backfilling empty cells,
                # instead of silently discarding the values
                self._widen_header(new_keys)
            with open(self.csv_path, "a", encoding="utf-8", newline="") as f:
                csv.DictWriter(
                    f, self._fieldnames, extrasaction="ignore"
                ).writerow(row)
        if self._tb is not None:
            for k, v in metrics.items():
                if isinstance(v, (int, float)):
                    self._tb.add_scalar(k, v, step)

    def _widen_header(self, new_keys: list) -> None:
        """Rewrite the CSV under a header widened by ``new_keys`` (atomic
        tmp + rename); existing rows get empty cells for the new columns."""
        with open(self.csv_path, "r", encoding="utf-8", newline="") as f:
            rows = list(csv.DictReader(f, fieldnames=self._fieldnames))
        if rows and list(rows[0].values())[: len(self._fieldnames)] == list(
            self._fieldnames
        ):
            rows = rows[1:]  # drop the header row DictReader re-parsed
        self._fieldnames = self._fieldnames + new_keys
        tmp = f"{self.csv_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8", newline="") as f:
            w = csv.DictWriter(f, self._fieldnames, extrasaction="ignore")
            w.writeheader()
            for r in rows:
                w.writerow({k: (v if v is not None else "") for k, v in r.items()})
        os.replace(tmp, self.csv_path)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
