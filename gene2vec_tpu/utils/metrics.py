"""Structured step metrics — CSV always, TensorBoard when available.

The reference logs through gensim INFO prints and TF1 summary writers
(loss/accuracy scalars + grad histograms, ``src/GGIPNN_Classification.py:
130-156``).  Here every trainer can emit one row per iteration/step to a
CSV next to its checkpoints, and mirror scalars to tensorboardX when that
package is installed.
"""

from __future__ import annotations

import csv
import os
import sys
import time
from typing import Dict, Optional


class MetricsLogger:
    """Append-only metrics log: CSV file + optional TensorBoard scalars."""

    def __init__(self, csv_path: Optional[str], tensorboard_dir: Optional[str] = None):
        self.csv_path = csv_path
        self._fieldnames: Optional[list] = None
        self._warned_dropped = False
        self._tb = None
        if csv_path:
            os.makedirs(os.path.dirname(os.path.abspath(csv_path)), exist_ok=True)
        if tensorboard_dir:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except ImportError:
                pass  # CSV remains the source of truth

    def log(self, step: int, metrics: Dict[str, float]) -> None:
        row = {"step": int(step), "time": time.time(), **metrics}
        if self.csv_path:
            new_fields = sorted(row)
            if self._fieldnames is None:
                exists = os.path.exists(self.csv_path)
                if exists:
                    with open(self.csv_path, "r", encoding="utf-8") as f:
                        header = f.readline().strip()
                    self._fieldnames = header.split(",") if header else new_fields
                else:
                    self._fieldnames = new_fields
                    with open(self.csv_path, "w", encoding="utf-8", newline="") as f:
                        csv.DictWriter(f, self._fieldnames).writeheader()
            dropped = set(row) - set(self._fieldnames)
            if dropped and not self._warned_dropped:
                self._warned_dropped = True
                print(
                    f"MetricsLogger: {self.csv_path} header lacks columns "
                    f"{sorted(dropped)}; their values are not recorded",
                    file=sys.stderr,
                )
            with open(self.csv_path, "a", encoding="utf-8", newline="") as f:
                csv.DictWriter(
                    f, self._fieldnames, extrasaction="ignore"
                ).writerow(row)
        if self._tb is not None:
            for k, v in metrics.items():
                if isinstance(v, (int, float)):
                    self._tb.add_scalar(k, v, step)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
