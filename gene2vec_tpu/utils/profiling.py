"""Profiling/observability.

The reference's only instrumentation is wall-clock prints around
load/shuffle/train (``src/gene2vec.py:40-55,77-83``).  Here: a step timer
that accumulates the north-star metric (gene-pairs/sec) and an optional
``jax.profiler`` trace context for real TPU profiles.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import List


@dataclass
class StepTimer:
    pairs: List[int] = field(default_factory=list)
    seconds: List[float] = field(default_factory=list)

    def record(self, num_pairs: int, elapsed_s: float) -> None:
        self.pairs.append(int(num_pairs))
        self.seconds.append(float(elapsed_s))

    @property
    def total_pairs(self) -> int:
        return sum(self.pairs)

    @property
    def total_seconds(self) -> float:
        return sum(self.seconds)

    def pairs_per_sec(self, skip_first: bool = True) -> float:
        """Throughput; drops the first record by default (it includes jit
        compilation)."""
        ps, ss = self.pairs, self.seconds
        if skip_first and len(ps) > 1:
            ps, ss = ps[1:], ss[1:]
        t = sum(ss)
        return sum(ps) / t if t > 0 else 0.0


@contextlib.contextmanager
def trace_context(log_dir: str | None):
    """``jax.profiler.trace`` when a log dir is given, else a no-op."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
