"""Incremental pair-corpus ingest with a durable, CRC-stamped cursor.

New GEO study batches arrive continuously; each is appended to ONE
append-only pair corpus (``<loop_root>/ingest/pairs.txt``) under a
commit protocol built on the resilience snapshot primitives
(docs/RESILIENCE.md failure model — a writer can die at ANY
instruction):

1. **Recover** — if ``pairs.txt`` is longer than the cursor's committed
   byte offset, a previous appender died mid-write: truncate back to
   the committed prefix (whose rolling CRC32 the cursor stamps, so
   post-commit rot is detected too, not just torn appends).
2. **Append** — the batch's pair lines are appended and fsync'd.
3. **Commit** — a new ``CURSOR.json`` (batch id, corpus byte offset,
   rolling corpus CRC32, vocab size — self-CRC-stamped, previous cursor
   kept as ``CURSOR.prev.json``) is written atomically LAST.  A SIGKILL
   anywhere before this leaves the batch uncommitted; the next attempt
   truncates and replays it.  Batch ids make replay idempotent.

**Vocab stability is the point.**  The vocabulary is always derived
deterministically as ``BASE_VOCAB.tsv`` (the serving model's vocab at
loop init — its id order IS the serving table's row order and the
fleet's gene→shard routing) extended by scanning the committed corpus
prefix in order: existing genes keep their ids (counts accumulate), new
genes append at the TAIL in first-appearance order.  Existing row ids
never move, so a warm-started candidate's first ``len(base)`` rows stay
aligned with the serving table.  When the ORIGINAL training corpus is
re-ingested as a batch (``replaces_base_counts=True`` — the CLI's
``--seed-corpus`` flow), the base counts are dropped and counts come
from the corpus scan alone: base counts already reflect that corpus,
and adding both would double every pre-existing gene's frequency and
skew the negative-sampling unigram distribution against new genes.

Study batches can come straight from ``corpus/builder.py``
(:func:`batch_from_study_dir` runs the per-study co-expression
thresholding pipeline) or as pre-built pair lines.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.resilience import snapshot as snap

CURSOR_SCHEMA = "gene2vec-tpu/loop-ingest-cursor/v1"
CURSOR_NAME = "CURSOR.json"
CURSOR_PREV_NAME = "CURSOR.prev.json"
BASE_VOCAB_NAME = "BASE_VOCAB.tsv"
PAIRS_NAME = "pairs.txt"

#: stable held-out fraction denominator for the quality gate's split
HOLDOUT_MOD = 1000


def ingest_dir(loop_root: str) -> str:
    return os.path.join(loop_root, "ingest")


def _cursor_payload_crc(doc: Dict) -> int:
    body = {k: v for k, v in sorted(doc.items()) if k != "cursor_crc32"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True).encode("utf-8")
    ) & 0xFFFFFFFF


def _empty_cursor() -> Dict:
    return {
        "schema": CURSOR_SCHEMA,
        "batches": [],
        "corpus_bytes": 0,
        "corpus_crc32": 0,
        "vocab_size": 0,
    }


def _write_cursor(idir: str, doc: Dict) -> None:
    doc = dict(doc)
    doc["cursor_crc32"] = _cursor_payload_crc(doc)
    cur = os.path.join(idir, CURSOR_NAME)
    if os.path.exists(cur):
        # keep the last good cursor: a cursor torn by post-write rot
        # falls back one commit instead of losing the whole offset
        with open(cur, "rb") as f:
            snap.atomic_write_bytes(
                os.path.join(idir, CURSOR_PREV_NAME), f.read()
            )
    snap.atomic_write_json(cur, doc)


def load_cursor(loop_root: str) -> Dict:
    """The newest readable, self-CRC-valid cursor (falling back to the
    previous commit, then to an empty cursor — an absent ingest store
    simply has nothing committed).  A store that clearly HAS committed
    data (non-empty ``pairs.txt``) but no valid cursor raises instead:
    treating it as fresh would let :func:`_recover` truncate the whole
    committed corpus to the empty cursor's zero offset."""
    idir = ingest_dir(loop_root)
    for name in (CURSOR_NAME, CURSOR_PREV_NAME):
        path = os.path.join(idir, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        if doc.get("cursor_crc32") != _cursor_payload_crc(doc):
            continue
        return doc
    pairs = os.path.join(idir, PAIRS_NAME)
    if os.path.exists(pairs) and os.path.getsize(pairs) > 0:
        raise IOError(
            f"{idir}: committed corpus present but no readable "
            "self-CRC-valid cursor (both CURSOR.json and "
            "CURSOR.prev.json lost/rotted) — restore a cursor before "
            "ingesting; proceeding would truncate the corpus"
        )
    return _empty_cursor()


def _recover(idir: str, cursor: Dict) -> None:
    """Enforce the cursor's committed prefix: truncate a torn append,
    verify the prefix CRC (post-commit rot raises — the corpus is the
    training input; training on rotted bytes silently would be worse
    than stopping)."""
    pairs = os.path.join(idir, PAIRS_NAME)
    committed = int(cursor.get("corpus_bytes", 0))
    size = os.path.getsize(pairs) if os.path.exists(pairs) else 0
    if size > committed:
        with open(pairs, "r+b") as f:
            f.truncate(committed)
            f.flush()
            os.fsync(f.fileno())
    elif size < committed:
        raise IOError(
            f"{pairs}: {size} bytes on disk but the cursor committed "
            f"{committed} — the corpus was truncated after commit"
        )
    if committed:
        crc = 0
        with open(pairs, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        if (crc & 0xFFFFFFFF) != int(cursor.get("corpus_crc32", 0)):
            raise IOError(
                f"{pairs}: committed prefix CRC mismatch — the corpus "
                "rotted after commit; restore it before ingesting"
            )


def init_ingest(loop_root: str, base_vocab: Vocab) -> bool:
    """Create the ingest store (idempotent).  ``base_vocab`` is the
    SERVING model's vocab — its id order anchors every future row id.
    Returns whether this call created the store."""
    idir = ingest_dir(loop_root)
    os.makedirs(idir, exist_ok=True)
    base_path = os.path.join(idir, BASE_VOCAB_NAME)
    if os.path.exists(base_path):
        return False
    snap.atomic_write_via(base_vocab.save, base_path)
    pairs = os.path.join(idir, PAIRS_NAME)
    if not os.path.exists(pairs):
        with open(pairs, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
    _write_cursor(idir, _empty_cursor())
    return True


def ingest_batch(
    loop_root: str, batch_id: str, lines: Sequence[str],
    replaces_base_counts: bool = False,
) -> Dict:
    """Append one study batch under the commit protocol (module doc).
    Idempotent by ``batch_id``: a committed batch is skipped, so a
    resumed loop cycle replays this step for free.  Returns the batch
    facts (pairs appended, new genes, committed corpus offset).

    ``replaces_base_counts`` marks this batch as a re-ingest of the
    corpus the serving model was trained on; once committed,
    :func:`loop_vocab` takes counts from the corpus scan alone (module
    doc).  The flag is sticky in the cursor — it survives SIGKILL and
    later batches."""
    idir = ingest_dir(loop_root)
    if not os.path.exists(os.path.join(idir, BASE_VOCAB_NAME)):
        raise FileNotFoundError(
            f"no ingest store under {loop_root!r} — call init_ingest "
            "with the serving model's vocab first"
        )
    cursor = load_cursor(loop_root)
    if batch_id in cursor.get("batches", []):
        # the cursor already committed this batch's vocab size — no
        # need to re-scan the whole (ever-growing) corpus on replay
        return {
            "batch_id": batch_id,
            "skipped": True,
            "appended_pairs": 0,
            "new_genes": 0,
            "vocab_size": int(cursor["vocab_size"]),
            "corpus_bytes": int(cursor["corpus_bytes"]),
        }
    _recover(idir, cursor)
    before = loop_vocab(loop_root)
    clean = [ln.strip() for ln in lines if ln.strip()]
    data = ("\n".join(clean) + "\n").encode("utf-8") if clean else b""
    pairs = os.path.join(idir, PAIRS_NAME)
    with open(pairs, "ab") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    snap.fsync_dir(idir)
    new_tokens = set()
    for ln in clean:
        for tok in ln.split():
            if tok not in before.token_to_id:
                new_tokens.add(tok)
    new_cursor = {
        "schema": CURSOR_SCHEMA,
        "batches": list(cursor.get("batches", [])) + [batch_id],
        "corpus_bytes": int(cursor["corpus_bytes"]) + len(data),
        "corpus_crc32": zlib.crc32(
            data, int(cursor.get("corpus_crc32", 0))
        ) & 0xFFFFFFFF,
        "vocab_size": len(before) + len(new_tokens),
        "base_counts_replaced": bool(
            cursor.get("base_counts_replaced")
        ) or replaces_base_counts,
    }
    _write_cursor(idir, new_cursor)
    return {
        "batch_id": batch_id,
        "skipped": False,
        "appended_pairs": len(clean),
        "new_genes": len(new_tokens),
        "vocab_size": new_cursor["vocab_size"],
        "corpus_bytes": new_cursor["corpus_bytes"],
    }


def _committed_lines(loop_root: str, cursor: Dict) -> List[List[str]]:
    """Token pairs from the committed corpus prefix only (bytes past
    the cursor belong to an uncommitted append and must not train)."""
    pairs = os.path.join(ingest_dir(loop_root), PAIRS_NAME)
    committed = int(cursor.get("corpus_bytes", 0))
    if committed == 0 or not os.path.exists(pairs):
        return []
    with open(pairs, "rb") as f:
        blob = f.read(committed)
    out = []
    for ln in blob.decode("utf-8").splitlines():
        toks = ln.split()
        if len(toks) >= 2:
            out.append(toks[:2])
    return out


def loop_vocab(loop_root: str, cursor: Optional[Dict] = None) -> Vocab:
    """The deterministic loop vocabulary: BASE_VOCAB's id order
    (counts included unless a ``replaces_base_counts`` batch committed
    — module doc), extended by the committed corpus in order —
    existing genes accumulate counts in place, new genes append at the
    tail in first-appearance order.  Recomputable from disk at any
    time, so a SIGKILL can never leave a half-extended vocab behind."""
    idir = ingest_dir(loop_root)
    base = Vocab.load(os.path.join(idir, BASE_VOCAB_NAME))
    cursor = cursor if cursor is not None else load_cursor(loop_root)
    tokens = list(base.id_to_token)
    if cursor.get("base_counts_replaced"):
        # the committed corpus contains the serving model's original
        # corpus (a replaces_base_counts batch): base supplies only the
        # id order — adding its counts too would double-count every
        # pre-existing gene (module doc)
        counts = {t: 0 for t in tokens}
    else:
        counts = {
            t: int(c) for t, c in zip(base.id_to_token, base.counts)
        }
    for a, b in _committed_lines(loop_root, cursor):
        for tok in (a, b):
            if tok not in counts:
                tokens.append(tok)
                counts[tok] = 0
            counts[tok] += 1
    return Vocab(tokens, np.asarray([counts[t] for t in tokens]))


def pair_held(a: str, b: str, fraction: float, salt: str = "loop") -> bool:
    """Stable holdout membership for the quality gate: keyed on the
    UNORDERED pair (both directions of one biological pair are held
    together — no leakage) and on the gene names, so the split never
    shifts as the corpus grows."""
    lo, hi = sorted((a, b))
    h = zlib.crc32(f"{salt}:{lo} {hi}".encode("utf-8")) % HOLDOUT_MOD
    return h < int(fraction * HOLDOUT_MOD)


def load_loop_corpus(
    loop_root: str, holdout_fraction: float = 0.2
) -> Tuple["object", List[List[str]]]:
    """(training PairCorpus, held-out pair list) over the committed
    corpus.  The held fraction (stable hash split, :func:`pair_held`)
    never trains — it is the quality gate's evaluation set."""
    from gene2vec_tpu.data.pipeline import PairCorpus

    cursor = load_cursor(loop_root)
    vocab = loop_vocab(loop_root, cursor)
    lines = _committed_lines(loop_root, cursor)
    train = [p for p in lines if not pair_held(*p, holdout_fraction)]
    held = [p for p in lines if pair_held(*p, holdout_fraction)]
    return PairCorpus(vocab, vocab.encode_pairs(train)), held


def batch_from_study_dir(query_dir: str, **build_kwargs) -> List[str]:
    """One study batch straight from the reference-format query dir via
    the corpus builder's per-study co-expression pipeline
    (``corpus/builder.py build_pairs`` — TPU-path correlation, same
    thresholding recipe as the original one-shot build)."""
    from gene2vec_tpu.corpus.builder import build_pairs

    return build_pairs(query_dir, out_path=None, **build_kwargs)
