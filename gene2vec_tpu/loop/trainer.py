"""Warm-start continued SGNS training + the candidate quality gate.

**Adoption makes warm start bit-exact for free.**  A continuation
cycle first *adopts* the serving export's latest VERIFIED iteration
into the cycle's candidate export dir: the tables are loaded
(manifest-checked), new-gene rows are seeded **deterministically**
(the `init_params(PRNGKey(seed), V_new, dim)` slice `[V_old:]` — a
pure function of (seed, V_new, dim), so every attempt at this cycle
seeds identical rows), and the result is saved as the SAME iteration
number under the candidate dir with the extended vocab.  Continued
training is then literally ``SGNSTrainer.run`` resuming from that
checkpoint — the RNG/config cursor in the manifest (seed, iteration →
``fold_in(PRNGKey(seed), it)``) replays the exact stream an
uninterrupted run would, so a SIGKILL anywhere mid-continuation
resumes bit-exact through the machinery the chaos drill has gated
since PR 4.  Adoption itself is idempotent (a candidate dir that
already has checkpoints skips it), so the whole step is re-entrant.

**Quality gate.**  Before a candidate is even eligible for shadowing
it must pass the intrinsic/holdout gate: holdout cosine AUC over the
ingest store's held-out pairs (stable hash split, loop/ingest.py)
against sampled negatives — two-sided, defaulting to the canonical
``eval/holdout.py`` band (``auc_in_gate_band``: scores far ABOVE the
oracle signal co-occurrence degeneration, not better embeddings) —
plus the reference's intrinsic target-function ratio
(``eval/target_function.py``) over held-out neighborhood sets.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.model import SGNSParams


def extend_params(
    params: SGNSParams, new_vocab: int, config: SGNSConfig,
    partition_rules=None, mesh=None,
) -> SGNSParams:
    """Seed rows for genes the checkpoint has never seen.  The new
    rows come from the init distribution at the NEW vocab size — a
    deterministic function of (config.seed, new_vocab, dim), so a
    resumed adoption and an uninterrupted one seed identical rows
    (the bit-exactness contract).  Existing rows pass through
    untouched; ctx rows init to zero exactly like a fresh table's.

    ``partition_rules`` (parallel/partition_rules.py) makes placement
    declarative: the extended tables round-trip through the
    rule-matched shardings — ``shard_params`` materializes rows on
    their owning devices, ``gather_params`` pulls the verified host
    copy back for the checkpoint writer — instead of the implicit
    default-device placement a bare ``device_put`` would pick."""
    import jax

    old = int(np.asarray(params.emb).shape[0])
    if new_vocab < old:
        raise ValueError(
            f"vocab shrank ({old} -> {new_vocab}); the loop only ever "
            "tail-extends"
        )
    if new_vocab == old:
        return params
    from gene2vec_tpu.sgns.model import init_params

    dim = int(np.asarray(params.emb).shape[1])
    full = init_params(
        jax.random.PRNGKey(config.seed), new_vocab, dim,
        np.asarray(params.emb).dtype,
    )
    emb = np.concatenate(
        [np.asarray(params.emb), np.asarray(full.emb)[old:]]
    )
    ctx = np.concatenate(
        [np.asarray(params.ctx),
         np.zeros((new_vocab - old, dim), np.asarray(params.ctx).dtype)]
    )
    if partition_rules is not None:
        from gene2vec_tpu.parallel.partition_rules import (
            gather_params,
            shard_params,
        )

        tree = shard_params(
            partition_rules, {"emb": emb, "ctx": ctx}, mesh=mesh
        )
        tree = gather_params(partition_rules, tree, mesh=mesh)
        emb = np.asarray(tree["emb"])
        ctx = np.asarray(tree["ctx"])
    return SGNSParams(emb=emb, ctx=ctx)


def candidate_base_iteration(
    candidate_dir: str, dim: int
) -> Optional[int]:
    """The iteration the candidate dir was ADOPTED at (its lowest
    checkpoint) — the warm-start anchor continued iteration counts
    derive from.  None for an un-adopted (empty) candidate dir."""
    its = [
        it for d, it, _ in ckpt.iter_checkpoints(
            candidate_dir, verified_only=True
        )
        if d == dim
    ]
    return min(its) if its else None


def adopt_checkpoint(
    serving_dir: str,
    candidate_dir: str,
    vocab: Vocab,
    config: SGNSConfig,
    log: Callable[[str], None] = lambda s: None,
    partition_rules=None,
    mesh=None,
) -> int:
    """Copy the serving export's latest verified iteration into the
    candidate dir with the (possibly tail-extended) loop vocab and
    deterministically seeded new-gene rows.  Idempotent: an already-
    adopted candidate dir returns its anchor unchanged.  Returns the
    adopted iteration number."""
    existing = candidate_base_iteration(candidate_dir, config.dim)
    if existing is not None:
        return existing
    base_it = ckpt.latest_iteration(serving_dir, config.dim)
    if base_it == 0:
        raise FileNotFoundError(
            f"no verified dim={config.dim} checkpoint in "
            f"{serving_dir!r} to warm-start from"
        )
    params, src_vocab, meta = ckpt.load_iteration(
        serving_dir, config.dim, base_it, table_dtype=config.table_dtype
    )
    if not ckpt.is_tail_extension(src_vocab.id_to_token, vocab.id_to_token):
        raise ValueError(
            "loop vocab is not a tail extension of the serving vocab — "
            "row ids would move; re-init the ingest store from the "
            "current serving model"
        )
    params = extend_params(
        params, len(vocab), config,
        partition_rules=partition_rules, mesh=mesh,
    )
    ckpt.save_iteration(
        candidate_dir, config.dim, base_it, params, vocab,
        txt_output=config.txt_output,
        meta={
            **{k: v for k, v in meta.items() if k in ("rng", "config_hash")},
            "warm_start": {
                "adopted_from": os.path.abspath(serving_dir),
                "adopted_iteration": base_it,
                "base_vocab_size": len(src_vocab),
                "new_genes": len(vocab) - len(src_vocab),
            },
        },
    )
    log(
        f"adopted iteration {base_it} from {serving_dir} "
        f"({len(src_vocab)} -> {len(vocab)} genes)"
    )
    return base_it


def train_candidate(
    serving_dir: str,
    candidate_dir: str,
    corpus,
    config: SGNSConfig,
    train_iters: int,
    preempt=None,
    log: Callable[[str], None] = lambda s: None,
) -> Tuple[SGNSParams, int, int]:
    """Warm-start + continue: adopt (idempotent), then run the standard
    trainer until ``anchor + train_iters`` — which IS the bit-exact
    resume path, so a SIGKILL mid-continuation and a fresh uninterrupted
    continuation converge on identical bytes.  Returns
    (final params, anchor iteration, final iteration)."""
    from gene2vec_tpu.sgns.train import SGNSTrainer

    base_it = adopt_checkpoint(
        serving_dir, candidate_dir, corpus.vocab, config, log=log
    )
    target = base_it + int(train_iters)
    cfg = dataclasses.replace(config, num_iters=target)
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.run(candidate_dir, log=log, preempt=preempt)
    return params, base_it, target


# -- the quality gate --------------------------------------------------------


def _negative_pairs(
    vocab: Vocab, positives: List[List[str]], n: int, seed: int
) -> List[List[str]]:
    """Seeded random in-vocab gene pairs excluding known positives —
    the AUC's negative class."""
    known = {tuple(sorted(p)) for p in positives}
    rng = np.random.RandomState(seed)
    tokens = vocab.id_to_token
    out: List[List[str]] = []
    guard = 0
    while len(out) < n and guard < 50 * n:
        guard += 1
        i, j = rng.randint(0, len(tokens), size=2)
        if i == j:
            continue
        a, b = tokens[i], tokens[j]
        if tuple(sorted((a, b))) in known:
            continue
        out.append([a, b])
    return out


def quality_report(
    vocab: Vocab,
    emb: np.ndarray,
    held_pairs: List[List[str]],
    min_auc: Optional[float] = None,
    max_auc: Optional[float] = None,
    seed: int = 7,
) -> dict:
    """The candidate's eligibility report: held-out cosine AUC (two-
    sided band; defaults to the canonical ``eval/holdout.py`` gate
    band) + the intrinsic target-function ratio over held-out
    neighborhood sets.  ``passed`` gates SHADOWING — a candidate that
    fails here is demoted without ever seeing traffic."""
    from gene2vec_tpu.eval.holdout import (
        GATE_MAX_AUC,
        GATE_MIN_AUC,
        cosine_scores,
    )
    from gene2vec_tpu.eval.metrics import roc_auc_score

    min_auc = GATE_MIN_AUC if min_auc is None else float(min_auc)
    max_auc = GATE_MAX_AUC if max_auc is None else float(max_auc)
    # de-duplicate direction twins: the builder emits (a,b) AND (b,a)
    uniq = sorted({tuple(sorted(p)) for p in held_pairs})
    positives = [list(p) for p in uniq]
    report: dict = {
        "held_pairs": len(positives),
        "min_auc": min_auc,
        "max_auc": max_auc,
        "auc": None,
        "intrinsic_ratio": None,
        "passed": False,
    }
    if len(positives) < 5:
        report["reason"] = (
            f"only {len(positives)} held-out pairs — not enough "
            "evidence to gate on"
        )
        return report
    negatives = _negative_pairs(vocab, positives, len(positives), seed)
    pairs = positives + negatives
    labels = np.asarray([1] * len(positives) + [0] * len(negatives))
    scores, mask = cosine_scores(vocab.token_to_id, emb, pairs)
    if mask.sum() < 10 or len(set(labels[mask].tolist())) < 2:
        report["reason"] = "too few in-vocab scored pairs"
        return report
    auc = float(roc_auc_score(labels[mask], scores[mask]))
    report["auc"] = round(auc, 4)
    # intrinsic ratio (reference targetFunc semantics) over held-out
    # neighborhood sets — informational unless degenerate, the AUC band
    # is the gate (QUALITY_NOTES §8: this ratio is undefined noise for
    # small set collections, so it cannot gate alone)
    try:
        from collections import defaultdict

        from gene2vec_tpu.eval.target_function import (
            pathway_similarities,
            random_pair_similarity,
        )

        nbrs = defaultdict(set)
        for a, b in positives:
            nbrs[a].add(b)
            nbrs[b].add(a)
        sets = {
            f"HELD_{g}": sorted(p)[:50]
            for g, p in nbrs.items() if len(p) >= 2
        }
        if sets:
            num, _ = pathway_similarities(vocab.id_to_token, emb, sets)
            den = random_pair_similarity(vocab.id_to_token, emb)
            if abs(den) > 1e-6:
                report["intrinsic_ratio"] = round(num / den, 4)
    except ValueError:
        pass
    report["passed"] = bool(min_auc <= auc <= max_auc)
    if not report["passed"]:
        report["reason"] = (
            f"holdout AUC {auc:.4f} outside the gate band "
            f"[{min_auc}, {max_auc}]"
        )
    return report
