"""Shadow-traffic canary: duplicate live traffic to a candidate replica.

The fleet front door (``serve/fleet.py``) calls
:meth:`ShadowManager.observe` after every successfully forwarded
``/v1/similar`` response.  A configurable sample of those requests is
duplicated to the CANDIDATE replica — a ``cli.serve`` process loaded
with the not-yet-promoted iteration — with three hard properties:

* **fire-and-forget**: the duplicate is enqueued onto a bounded worker
  queue; a full queue drops the sample (counted) and the live caller's
  latency path never pays a microsecond of shadow work;
* **same trace**: the shadow leg carries a child context of the live
  request's traceparent, so ``cli.obs trace`` renders live and shadow
  as sibling subtrees of one request;
* **scored**: a :class:`ShadowScorer` diffs each pair of answers —
  top-k Jaccard answer churn, rank displacement over the common
  neighbors — and tracks both arms' latency distributions, so the
  promotion gate reads answer churn and p99 delta straight off the
  report.

The manager doubles as the front door's ``/v1/shadow/*`` admin surface
(start/stop/report), which is how ``cli.loop`` drives a canary inside
a running fleet without restarting it.
"""

from __future__ import annotations

import json
import queue as queue_mod
import random
import threading
import time
import urllib.request
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from gene2vec_tpu.obs.tracecontext import TraceContext


def _default_fetch(
    url: str, method: str, target: str, body: Optional[dict],
    headers: Dict[str, str], timeout_s: float,
) -> Tuple[int, bytes]:
    data = None
    if method == "POST":
        data = json.dumps(body or {}).encode("utf-8")
        headers = {**headers, "Content-Type": "application/json"}
    req = urllib.request.Request(
        url + target, data=data, headers=headers, method=method
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.status, resp.read()


def topk_churn(
    live: List[str], shadow: List[str]
) -> Tuple[float, Optional[float]]:
    """(Jaccard answer churn, mean rank displacement / k over the
    common neighbors).  Churn 0.0 = identical sets, 1.0 = disjoint;
    displacement None when the arms share nothing."""
    ls, ss = set(live), set(shadow)
    union = ls | ss
    if not union:
        return 0.0, 0.0
    churn = 1.0 - len(ls & ss) / len(union)
    common = ls & ss
    if not common:
        return churn, None
    k = max(len(live), len(shadow), 1)
    li = {g: i for i, g in enumerate(live)}
    si = {g: i for i, g in enumerate(shadow)}
    disp = sum(abs(li[g] - si[g]) for g in common) / (len(common) * k)
    return churn, disp


def _p99(samples: Iterable[float]) -> Optional[float]:
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


class ShadowScorer:
    """Aggregates per-request live-vs-shadow diffs.  Thread-safe;
    bounded rings so a long canary window cannot grow without limit."""

    def __init__(self, max_samples: int = 4096):
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.scored = 0
            self.errors = 0
            self.churn_sum = 0.0
            self.churn_max = 0.0
            self.churn_n = 0
            self.disp_sum = 0.0
            self.disp_n = 0
            # deque rings: a window longer than max_samples keeps the
            # NEWEST latencies (a candidate that degrades late must
            # show in p99), not the first-N frozen snapshot
            self.live_s: Deque[float] = deque(maxlen=self.max_samples)
            self.shadow_s: Deque[float] = deque(maxlen=self.max_samples)
            self.live_iterations: set = set()
            self.shadow_iterations: set = set()

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def score(
        self, live_doc: dict, shadow_doc: dict,
        live_s: float, shadow_s: float,
    ) -> None:
        """Diff one pair of /v1/similar response documents."""
        lr = live_doc.get("results") or []
        sr = shadow_doc.get("results") or []
        churns: List[float] = []
        disps: List[float] = []
        for lq, sq in zip(lr, sr):
            ln = [n.get("gene") for n in (lq.get("neighbors") or [])]
            sn = [n.get("gene") for n in (sq.get("neighbors") or [])]
            c, d = topk_churn(ln, sn)
            churns.append(c)
            if d is not None:
                disps.append(d)
        with self._lock:
            self.scored += 1
            for c in churns:
                self.churn_sum += c
                self.churn_max = max(self.churn_max, c)
            self.churn_n += len(churns)
            self.disp_n += len(disps)
            for d in disps:
                self.disp_sum += d
            self.live_s.append(live_s)
            self.shadow_s.append(shadow_s)
            lit = (live_doc.get("model") or {}).get("iteration")
            sit = (shadow_doc.get("model") or {}).get("iteration")
            if lit is not None:
                self.live_iterations.add(lit)
            if sit is not None:
                self.shadow_iterations.add(sit)

    def report(self) -> dict:
        with self._lock:
            p99_live = _p99(self.live_s)
            p99_shadow = _p99(self.shadow_s)
            return {
                "scored": self.scored,
                "errors": self.errors,
                "answer_churn": (
                    round(self.churn_sum / self.churn_n, 4)
                    if self.churn_n else None
                ),
                "answer_churn_max": round(self.churn_max, 4),
                "rank_displacement": (
                    round(self.disp_sum / self.disp_n, 4)
                    if self.disp_n else None
                ),
                "p99_live_ms": (
                    round(p99_live * 1000.0, 3)
                    if p99_live is not None else None
                ),
                "p99_shadow_ms": (
                    round(p99_shadow * 1000.0, 3)
                    if p99_shadow is not None else None
                ),
                "p99_delta_ms": (
                    round((p99_shadow - p99_live) * 1000.0, 3)
                    if p99_live is not None and p99_shadow is not None
                    else None
                ),
                "live_iterations": sorted(self.live_iterations),
                "shadow_iterations": sorted(self.shadow_iterations),
            }


class ShadowManager:
    """The fleet front door's canary engine + ``/v1/shadow/*`` admin
    surface.  Inactive (no target) until ``start`` — observe() is then
    a single predicate, so a fleet with shadowing enabled but no
    canary in flight pays nothing."""

    def __init__(
        self,
        metrics=None,
        workers: int = 2,
        queue_max: int = 256,
        fetch=_default_fetch,
        shadow_timeout_s: float = 5.0,
    ):
        self.metrics = metrics
        self.fetch = fetch
        self.shadow_timeout_s = shadow_timeout_s
        self.queue_max = int(queue_max)
        self.scorer = ShadowScorer()
        self._rng = random.Random()
        self._lock = threading.Lock()
        self._target: Optional[str] = None
        self._sample = 0.0
        # canary-window generation: bumped on every start/stop so jobs
        # enqueued (or in flight) for a previous window can never score
        # into a freshly reset scorecard
        self._gen = 0
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_max)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        for i in range(int(workers)):
            t = threading.Thread(
                target=self._worker, name=f"shadow-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _count(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc(n)

    # -- admin surface (the proxy's /v1/shadow/* routes) -------------------

    def start(self, url: str, sample: float = 0.1) -> dict:
        """Point the canary at a candidate replica and reset the
        scorecard.  ``sample`` is the duplicated fraction of live
        /v1/similar traffic."""
        if not isinstance(url, str) or not url.startswith("http"):
            raise ValueError(f"bad shadow target url {url!r}")
        sample = float(sample)
        if not 0.0 < sample <= 1.0:
            raise ValueError("sample must be in (0, 1]")
        with self._lock:
            self._target = url.rstrip("/")
            self._sample = sample
            self._gen += 1
            # reset INSIDE the lock: the workers' gen-check + score is
            # also lock-held, so a stale worker can never interleave
            # between the bump and the reset
            self.scorer.reset()
        if self.metrics is not None:
            self.metrics.gauge("shadow_active").set(1)
        return {"shadowing": True, "url": self._target, "sample": sample}

    def stop(self) -> dict:
        with self._lock:
            self._target = None
            self._sample = 0.0
            self._gen += 1
        if self.metrics is not None:
            self.metrics.gauge("shadow_active").set(0)
        return {"shadowing": False, "report": self.scorer.report()}

    def report(self) -> dict:
        with self._lock:
            target, sample = self._target, self._sample
        return {
            "shadowing": target is not None,
            "url": target,
            "sample": sample,
            "report": self.scorer.report(),
        }

    def admin(self, method: str, route: str,
              body: Optional[dict]) -> Tuple[int, dict]:
        """Dispatch one /v1/shadow/* admin request."""
        try:
            if method == "POST" and route == "/v1/shadow/start":
                body = body or {}
                return 200, self.start(
                    body.get("url"), body.get("sample", 0.1)
                )
            if method == "POST" and route == "/v1/shadow/stop":
                return 200, self.stop()
            if method == "GET" and route == "/v1/shadow/report":
                return 200, self.report()
        except ValueError as e:
            return 400, {"error": str(e)}
        return 404, {"error": f"no shadow route {method} {route}"}

    # -- the data path ------------------------------------------------------

    def observe(
        self,
        method: str,
        target: str,
        body: Optional[dict],
        live_raw: Optional[bytes],
        live_s: float,
        ctx: Optional[TraceContext],
    ) -> None:
        """Called by the proxy AFTER a successful live forward.  Cheap
        by contract: one predicate + one bounded put; everything
        heavier happens on the worker threads."""
        with self._lock:
            url, sample, gen = self._target, self._sample, self._gen
        if url is None or self._rng.random() >= sample:
            return
        try:
            self._q.put_nowait(
                (gen, url, method, target, body, live_raw, live_s, ctx)
            )
        except queue_mod.Full:
            self._count("shadow_dropped_total")

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._q.get(timeout=0.25)
            except queue_mod.Empty:
                continue
            gen, url, method, target, body, live_raw, live_s, ctx = job
            with self._lock:
                if gen != self._gen:
                    # stale job from a previous canary window — fetching
                    # a gone candidate or scoring an old target's answer
                    # would contaminate the new window's verdict
                    continue
            self._count("shadow_requests_total")
            headers: Dict[str, str] = {}
            if ctx is not None:
                # sibling subtree of the live request: same trace id,
                # child span — cli.obs trace renders both arms together
                headers["traceparent"] = ctx.child().to_header()
            t0 = time.monotonic()
            try:
                status, raw = self.fetch(
                    url, method, target, body, headers,
                    self.shadow_timeout_s,
                )
                shadow_s = time.monotonic() - t0
                if not 200 <= status < 300:
                    raise IOError(f"shadow leg status {status}")
                live_doc = json.loads((live_raw or b"{}").decode("utf-8"))
                shadow_doc = json.loads(raw.decode("utf-8"))
                with self._lock:
                    if gen != self._gen:
                        continue  # window turned over mid-fetch
                    self.scorer.score(
                        live_doc, shadow_doc, live_s, shadow_s
                    )
            except Exception:
                with self._lock:
                    if gen != self._gen:
                        continue  # stale window's error is not evidence
                    self._count("shadow_errors_total")
                    self.scorer.record_error()

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
