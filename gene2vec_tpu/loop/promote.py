"""The promotion controller: a journaled, resumable loop state machine.

States, in order::

    INGESTING → TRAINING → QUALITY_GATE → SHADOWING → PROMOTING → SERVING

with ``DEMOTED`` as the terminal failure branch (a candidate that
fails the quality gate or the shadow budgets is quarantined — moved
under ``<loop_root>/quarantine/`` — and the fleet keeps serving the
live iteration untouched).

Every transition is journaled to ``<loop_root>/loop_runs/<cycle>/
loop.jsonl`` — one JSON record per line, appended + fsync'd, a torn
final line (SIGKILL mid-append) ignored on replay.  The journal is the
resume cursor: a killed cycle re-runs only the states that never
recorded ``done``, and each state's step is itself idempotent (the
ingest cursor, the checkpoint resume machinery, the epoch-token swap),
so a SIGKILL in ANY state resumes instead of retraining from scratch.

The driver is deliberately process-agnostic: ``cli.loop`` wires the
real steps (ingest store, warm-start trainer, fleet shadow admin,
publish + swap-wait) and tests wire fakes.  ``crash_at`` is the chaos
hook the drill uses — a REAL ``SIGKILL`` to our own pid immediately
after the state's ``enter`` record commits.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Callable, Dict, List, Optional


class LoopState:
    INGESTING = "INGESTING"
    TRAINING = "TRAINING"
    QUALITY_GATE = "QUALITY_GATE"
    SHADOWING = "SHADOWING"
    PROMOTING = "PROMOTING"
    SERVING = "SERVING"
    DEMOTED = "DEMOTED"


STATE_ORDER = (
    LoopState.INGESTING,
    LoopState.TRAINING,
    LoopState.QUALITY_GATE,
    LoopState.SHADOWING,
    LoopState.PROMOTING,
    LoopState.SERVING,
)

JOURNAL_NAME = "loop.jsonl"
JOURNAL_SCHEMA = "gene2vec-tpu/loop-journal/v1"


def journal_path(loop_root: str, cycle_id: str) -> str:
    return os.path.join(loop_root, "loop_runs", cycle_id, JOURNAL_NAME)


class LoopJournal:
    """Append-only transition log; the cycle's durable resume cursor.

    Records: ``{"schema", "cycle", "seq", "wall", "state", "event":
    "enter"|"done", "facts": {...}}``.  Appends fsync before returning
    — a record the caller saw committed survives a SIGKILL; a torn
    final line is dropped by :meth:`replay` (it was never committed)."""

    def __init__(self, path: str, cycle_id: str):
        self.path = path
        self.cycle_id = cycle_id
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._seq = len(self.replay())

    def _repair_tail(self) -> None:
        # A writer SIGKILLed mid-append leaves a torn final line with
        # no trailing newline; appending onto it would merge two
        # records into one line and turn a droppable tear into
        # pre-final corruption that replay() must raise on.  Truncate
        # back to the last committed (newline-terminated) record.
        try:
            if os.path.getsize(self.path) == 0:
                return
        except OSError:
            return
        with open(self.path, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            f.seek(0)
            f.truncate(f.read().rfind(b"\n") + 1)

    def _append(self, record: Dict) -> None:
        line = json.dumps(record, default=str)
        self._repair_tail()
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._seq += 1

    def enter(self, state: str, **facts) -> None:
        self._append({
            "schema": JOURNAL_SCHEMA, "cycle": self.cycle_id,
            "seq": self._seq, "wall": time.time(),
            "state": state, "event": "enter", "facts": facts,
        })

    def done(self, state: str, **facts) -> None:
        self._append({
            "schema": JOURNAL_SCHEMA, "cycle": self.cycle_id,
            "seq": self._seq, "wall": time.time(),
            "state": state, "event": "done", "facts": facts,
        })

    def replay(self) -> List[Dict]:
        """Committed records, oldest first.  A torn/unparseable final
        line is ignored — the writer died mid-append and the record
        never committed; a torn line anywhere EARLIER means post-commit
        corruption and raises."""
        if not os.path.exists(self.path):
            return []
        out: List[Dict] = []
        with open(self.path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1:
                    break
                raise IOError(
                    f"{self.path}:{i + 1}: corrupt journal record "
                    "before the final line"
                )
        return out

    def done_facts(self) -> Dict[str, Dict]:
        """state → facts of its committed ``done`` record."""
        return {
            r["state"]: r.get("facts", {})
            for r in self.replay() if r.get("event") == "done"
        }

    def state_walls(self) -> Dict[str, Dict[str, float]]:
        """state → {"enter": wall, "done": wall} (for latency facts)."""
        out: Dict[str, Dict[str, float]] = {}
        for r in self.replay():
            out.setdefault(r["state"], {})[r["event"]] = r.get("wall")
        return out


def quarantine_candidate(loop_root: str, candidate_dir: str,
                         cycle_id: str) -> Optional[str]:
    """Move a demoted candidate export under ``<loop_root>/quarantine``
    — it must never become discoverable by serving, but the bytes stay
    for the post-mortem."""
    if not os.path.isdir(candidate_dir):
        return None
    qdir = os.path.join(loop_root, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    dst = os.path.join(qdir, f"{cycle_id}_{int(time.time())}")
    shutil.move(candidate_dir, dst)
    return dst


class CycleDriver:
    """Run (or resume) one loop cycle.

    ``steps`` maps each state in :data:`STATE_ORDER` to a callable
    ``fn(context) -> facts`` where ``context`` carries every earlier
    state's committed facts (keyed by state name).  QUALITY_GATE facts
    must include ``passed``; SHADOWING facts must include ``verdict``
    (``"promote"`` | ``"demote"``) — a failing gate or a demote verdict
    branches to DEMOTED, which runs the optional ``demote`` step
    (quarantine) and terminates the cycle.

    ``crash_at`` (the chaos drill's hook): SIGKILL our own process the
    moment the named state's ``enter`` record commits — a genuine
    crash, not an exception path.
    """

    def __init__(
        self,
        journal: LoopJournal,
        steps: Dict[str, Callable[[Dict], Dict]],
        demote_step: Optional[Callable[[Dict], Dict]] = None,
        crash_at: Optional[str] = None,
        log: Callable[[str], None] = lambda s: None,
    ):
        self.journal = journal
        self.steps = steps
        self.demote_step = demote_step
        self.crash_at = crash_at
        self.log = log

    def _maybe_crash(self, state: str) -> None:
        if self.crash_at == state:
            import signal

            self.log(f"CHAOS: SIGKILL self at {state}")
            os.kill(os.getpid(), signal.SIGKILL)

    def _demote(self, context: Dict, reason: str) -> Dict:
        self.journal.enter(LoopState.DEMOTED, reason=reason)
        self._maybe_crash(LoopState.DEMOTED)
        facts = (
            self.demote_step(context) if self.demote_step is not None
            else {}
        )
        facts = dict(facts, reason=reason)
        self.journal.done(LoopState.DEMOTED, **facts)
        context[LoopState.DEMOTED] = facts
        return {"state": LoopState.DEMOTED, "context": context}

    def run(self) -> Dict:
        """Advance to a terminal state (SERVING or DEMOTED); returns
        ``{"state": terminal, "context": {state: facts}}``."""
        done = self.journal.done_facts()
        context: Dict[str, Dict] = dict(done)
        if LoopState.DEMOTED in done:
            return {"state": LoopState.DEMOTED, "context": context}
        if LoopState.SERVING in done:
            return {"state": LoopState.SERVING, "context": context}
        for state in STATE_ORDER:
            if state in context:
                # committed by an earlier attempt: honor its branch,
                # never re-run the work
                facts = context[state]
                if state == LoopState.QUALITY_GATE and not facts.get(
                    "passed"
                ):
                    return self._demote(
                        context, facts.get("reason", "quality gate failed")
                    )
                if state == LoopState.SHADOWING and facts.get(
                    "verdict"
                ) != "promote":
                    return self._demote(
                        context, facts.get("reason", "shadow verdict demote")
                    )
                continue
            self.log(f"state: {state}")
            self.journal.enter(state)
            self._maybe_crash(state)
            facts = self.steps[state](context) or {}
            self.journal.done(state, **facts)
            context[state] = facts
            if state == LoopState.QUALITY_GATE and not facts.get("passed"):
                return self._demote(
                    context, facts.get("reason", "quality gate failed")
                )
            if state == LoopState.SHADOWING and facts.get(
                "verdict"
            ) != "promote":
                return self._demote(
                    context, facts.get("reason", "shadow verdict demote")
                )
        return {"state": LoopState.SERVING, "context": context}
