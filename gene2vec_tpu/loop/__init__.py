"""Continuous-learning loop (docs/CONTINUOUS.md).

The reference is a one-shot batch pipeline; production is a loop:

    ingest.py   — incremental study-batch ingest with a durable,
                  CRC-stamped cursor (SIGKILL mid-append never produces
                  a half-counted batch; new genes extend the vocab TAIL
                  so existing row ids stay stable)
    trainer.py  — warm-start continued SGNS from the latest verified
                  checkpoint (bit-exact with an uninterrupted run, new
                  gene rows seeded deterministically) + the intrinsic/
                  holdout quality gate
    shadow.py   — shadow-traffic canary: the fleet front door
                  duplicates a sample of live /v1/similar traffic to a
                  candidate replica off the caller's latency path and
                  diffs answer churn + latency between arms
    promote.py  — the journaled state machine (INGESTING → TRAINING →
                  QUALITY_GATE → SHADOWING → PROMOTING → SERVING, or
                  DEMOTED) that promotes through the existing swap
                  protocols only inside budgets.json "loop" bounds

``python -m gene2vec_tpu.cli.loop`` drives one cycle against a real
fleet; ``scripts/chaos_drill.py --only loop`` rehearses it with a
SIGKILL in every state.
"""
