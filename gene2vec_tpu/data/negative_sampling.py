"""Unigram^0.75 negative sampling, TPU-resident.

gensim materializes a 100M-entry cumulative table and draws by indexing
random positions into it (the Cython hot loop behind ``src/gene2vec.py:70``).
On TPU we keep only the V-entry cumulative distribution in HBM and draw by
``searchsorted`` on uniform variates — O(log V) per draw, fully vectorized,
and exact rather than quantized to table resolution.

Collision semantics: gensim skips a negative draw when it equals the positive
target word.  We mask such draws out of the loss/update instead (their
gradient contribution is zeroed), which preserves the expectation without a
data-dependent resampling loop that XLA could not compile statically.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def noise_distribution(counts: np.ndarray, ns_exponent: float = 0.75) -> np.ndarray:
    """Normalized unigram^ns_exponent noise distribution over the vocab."""
    p = np.asarray(counts, dtype=np.float64) ** ns_exponent
    return (p / p.sum()).astype(np.float32)


class NegativeSampler:
    """Batched categorical sampler via inverse-CDF searchsorted."""

    def __init__(self, counts: np.ndarray, ns_exponent: float = 0.75):
        probs = noise_distribution(counts, ns_exponent)
        # float64 cumsum on host for accuracy, then f32 on device; clamp the
        # final entry to 1 so searchsorted can never fall off the end.
        cdf = np.cumsum(probs.astype(np.float64))
        cdf[-1] = 1.0
        self.cdf = jnp.asarray(cdf, dtype=jnp.float32)
        self.vocab_size = int(len(probs))

    def sample(self, key: jax.Array, shape) -> jax.Array:
        """Draw int32 token ids with the noise distribution."""
        u = jax.random.uniform(key, shape, dtype=jnp.float32)
        idx = jnp.searchsorted(self.cdf, u, side="right")
        return jnp.clip(idx, 0, self.vocab_size - 1).astype(jnp.int32)


def sample_negatives(cdf: jax.Array, key: jax.Array, shape) -> jax.Array:
    """Functional form of :meth:`NegativeSampler.sample` for use inside
    jitted training steps (cdf passed as a traced array)."""
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    idx = jnp.searchsorted(cdf, u, side="right")
    return jnp.clip(idx, 0, cdf.shape[0] - 1).astype(jnp.int32)
