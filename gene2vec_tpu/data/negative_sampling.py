"""Unigram^0.75 negative sampling, TPU-resident, via the alias method.

gensim materializes a 100M-entry cumulative table and draws by indexing
random positions into it (the Cython hot loop behind ``src/gene2vec.py:70``).
A first TPU port used inverse-CDF ``searchsorted``, but binary search is a
serial gather chain — it measured ~22 ms per 160k draws on v5e, dominating
the whole training step.  The Vose alias table draws in O(1): one uniform
index, one coin flip, two scalar gathers — ~6x faster end to end, and exact
(no quantization to table resolution).

Collision semantics: gensim skips a negative draw when it equals the positive
target word.  We mask such draws out of the loss/update instead (their
gradient contribution is zeroed), which preserves the expectation without a
data-dependent resampling loop that XLA could not compile statically.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp


def noise_distribution(counts: np.ndarray, ns_exponent: float = 0.75) -> np.ndarray:
    """Normalized unigram^ns_exponent noise distribution over the vocab."""
    p = np.asarray(counts, dtype=np.float64) ** ns_exponent
    return (p / p.sum()).astype(np.float32)


class NoiseTable(NamedTuple):
    """Vose alias table: draw j ~ U[0,V), keep j with prob[j] else alias[j]."""

    prob: jax.Array   # (V,) float32 — acceptance probability per slot
    alias: jax.Array  # (V,) int32 — fallback token per slot

    @property
    def vocab_size(self) -> int:
        return int(self.prob.shape[0])


def build_alias_table(probs: np.ndarray) -> NoiseTable:
    """Host-side O(V) Vose construction."""
    p = np.asarray(probs, dtype=np.float64)
    if p.ndim != 1 or p.size == 0:
        raise ValueError("probs must be a non-empty 1-D distribution")
    v = p.size
    scaled = p * v / p.sum()
    prob = np.ones(v, dtype=np.float64)
    alias = np.arange(v, dtype=np.int64)
    small = [i for i in range(v) if scaled[i] < 1.0]
    large = [i for i in range(v) if scaled[i] >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] -= 1.0 - scaled[s]
        (small if scaled[l] < 1.0 else large).append(l)
    # leftovers (float round-off) keep prob 1 / self alias
    return NoiseTable(
        prob=jnp.asarray(prob, jnp.float32), alias=jnp.asarray(alias, jnp.int32)
    )


class NegativeSampler:
    """Batched categorical sampler over unigram^ns_exponent counts."""

    def __init__(self, counts: np.ndarray, ns_exponent: float = 0.75):
        self.probs = noise_distribution(counts, ns_exponent)
        self.table = build_alias_table(self.probs)
        self.vocab_size = int(len(self.probs))

    def sample(self, key: jax.Array, shape) -> jax.Array:
        """Draw int32 token ids with the noise distribution."""
        return sample_negatives(self.table, key, shape)


def sample_negatives(table: NoiseTable, key: jax.Array, shape) -> jax.Array:
    """Functional alias-method draw for use inside jitted training steps."""
    kj, kc = jax.random.split(key)
    j = jax.random.randint(kj, shape, 0, table.prob.shape[0], dtype=jnp.int32)
    coin = jax.random.uniform(kc, shape, dtype=jnp.float32)
    return jnp.where(coin < table.prob[j], j, table.alias[j]).astype(jnp.int32)


class StratifiedSpec:
    """Precomputed layout for ``negative_mode="stratified"`` (round-3 perf
    design, docs/PERF_NOTES.md): the frequency-sorted vocab splits into an
    exact HEAD — rows [0, head) contribute their noise-expectation term
    K*q_j*softplus(v.u_j) densely, zero sampling variance, no scatter —
    and a TAIL partitioned into ``nb`` contiguous blocks of ``block`` rows
    (the last block clamps to the vocab end and may overlap its
    predecessor).  Each example group draws one block uniformly;
    ``tail_w[j] = q_j / p_j`` pre-divides each row's noise weight by its
    draw probability p_j = (blocks containing j)/nb, so the estimator is
    unbiased row-by-row including the overlap.

    Registered as a pytree with the arrays as children and the geometry as
    static aux data, so it can flow through jit boundaries while shapes
    stay compile-time constants.
    """

    def __init__(self, q, tail_w, head: int, block: int, nb: int):
        self.q = q
        self.tail_w = tail_w
        self.head = int(head)
        self.block = int(block)
        self.nb = int(nb)

    def tree_flatten(self):
        return (self.q, self.tail_w), (self.head, self.block, self.nb)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


jax.tree_util.register_pytree_node(
    StratifiedSpec,
    StratifiedSpec.tree_flatten,
    StratifiedSpec.tree_unflatten,
)


def build_stratified_spec(
    counts: np.ndarray,
    head: int = 256,
    block: int = 128,
    ns_exponent: float = 0.75,
) -> StratifiedSpec:
    """Host-side construction; clamps geometry for small vocabs (head to
    half the vocab, block to the tail size) so every vocab works — a tiny
    vocab degenerates to near-exact negatives (head exact, one tail block
    always drawn)."""
    q = noise_distribution(counts, ns_exponent)
    v = q.shape[0]
    head = max(1, min(head, v // 2))
    block = max(1, min(block, v - head))
    t = v - head
    nb = -(-t // block)  # ceil: last block start clamps to v - block
    starts = np.minimum(head + np.arange(nb) * block, v - block)
    coverage = np.zeros(v, np.int64)
    for s in starts:
        coverage[s : s + block] += 1
    tail_w = np.zeros(v, np.float32)
    tail = coverage > 0
    tail_w[tail] = q[tail] * nb / coverage[tail]
    return StratifiedSpec(
        q=jnp.asarray(q),
        tail_w=jnp.asarray(tail_w),
        head=head,
        block=block,
        nb=nb,
    )
