from gene2vec_tpu.data.negative_sampling import (  # noqa: F401
    build_alias_table,
    noise_distribution,
    NegativeSampler,
    NoiseTable,
)
from gene2vec_tpu.data.pipeline import PairCorpus  # noqa: F401
