from gene2vec_tpu.data.negative_sampling import (  # noqa: F401
    noise_distribution,
    NegativeSampler,
)
from gene2vec_tpu.data.pipeline import PairCorpus  # noqa: F401
