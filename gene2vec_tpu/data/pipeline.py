"""Pair-stream pipeline: the corpus lives in HBM, shuffling happens on device.

The reference keeps the whole corpus as a Python list of 2-element lists and
reshuffles it with ``random.shuffle`` every iteration (``src/gene2vec.py:32-52,80``)
— hundreds of millions of Python objects.  Here the encoded corpus is one
(N, 2) int32 device array; an epoch's shuffle is a ``jax.random.permutation``
folded into the jitted epoch scan, so the host never touches pair data after
the initial upload.

Batching drops the ragged tail (< batch_pairs pairs) of each epoch — with the
per-epoch reshuffle every pair still gets seen in expectation, and static
shapes are what keep XLA from recompiling.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.io.vocab import Vocab


class PairCorpus:
    """Encoded pair corpus + vocab, with device-resident batching helpers."""

    def __init__(self, vocab: Vocab, pairs: np.ndarray):
        pairs = np.asarray(pairs, dtype=np.int32)
        if pairs.ndim != 2 or pairs.shape[1] != 2:
            raise ValueError(f"pairs must be (N, 2), got {pairs.shape}")
        self.vocab = vocab
        self.pairs = pairs

    @property
    def num_pairs(self) -> int:
        return int(self.pairs.shape[0])

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def num_batches(self, batch_pairs: int) -> int:
        return self.num_pairs // batch_pairs

    def device_pairs(self, sharding: Optional[jax.sharding.Sharding] = None) -> jax.Array:
        """Upload the corpus once; optionally sharded over the data axis."""
        if sharding is not None:
            return jax.device_put(self.pairs, sharding)
        return jnp.asarray(self.pairs)

    def pad_to_multiple(self, multiple: int) -> "PairCorpus":
        """Pad (by wrapping around) so num_pairs is divisible by ``multiple``
        — needed to shard the corpus evenly across data-parallel devices."""
        n = self.num_pairs
        rem = n % multiple
        if rem == 0:
            return self
        extra = self.pairs[: multiple - rem]
        return PairCorpus(self.vocab, np.concatenate([self.pairs, extra], axis=0))

    def process_shard(
        self, index: Optional[int] = None, count: Optional[int] = None
    ) -> "PairCorpus":
        """This host's strided shard of the corpus for multi-host SPMD runs
        (docs/DISTRIBUTED.md): every host reads the same files, keeps rows
        ``index::count``, and feeds only its shard of the global batch.
        Strided (not blocked) so hosts' shards interleave the corpus order
        and the per-epoch shuffle stays well-mixed globally.  Defaults to
        ``jax.process_index()``/``jax.process_count()``; identity on a
        single-process run.  Vocab (built from the FULL corpus) is shared —
        call before any per-host padding.

        Every shard is trimmed to exactly ``num_pairs // count`` rows: the
        trainer derives ``num_batches`` (and the small-corpus batch shrink)
        from its *local* shard, so hosts whose shards differed by one row
        could compile different epoch step counts and deadlock the SPMD
        collectives.  Dropping < count tail rows is harmless — the per-epoch
        reshuffle already drops ragged batch tails by design."""
        if index is None:
            index = jax.process_index()
        if count is None:
            count = jax.process_count()
        if count < 1:
            # a buggy launcher (unset env parsed as 0) must not silently
            # feed every host the full corpus
            raise ValueError(f"process count must be >= 1, got {count}")
        if not 0 <= index < count:
            raise ValueError(f"process index {index} not in [0, {count})")
        if count == 1:
            return self
        per_host = self.num_pairs // count
        return PairCorpus(self.vocab, self.pairs[index::count][:per_host])

    def host_batches(
        self, batch_pairs: int, rng: np.random.Generator, shuffle: bool = True
    ) -> Iterator[np.ndarray]:
        """Host-side batch iterator (CPU oracle paths / tests)."""
        order = (
            rng.permutation(self.num_pairs) if shuffle else np.arange(self.num_pairs)
        )
        for b in range(self.num_batches(batch_pairs)):
            yield self.pairs[order[b * batch_pairs : (b + 1) * batch_pairs]]


def epoch_permutation(key: jax.Array, num_pairs: int, batch_pairs: int) -> jax.Array:
    """(num_batches, batch_pairs) int32 shuffled index matrix for one epoch —
    the device-side equivalent of the reference's per-iteration
    ``random.shuffle(gene_pairs)`` (``src/gene2vec.py:80``)."""
    num_batches = num_pairs // batch_pairs
    perm = jax.random.permutation(key, num_pairs)[: num_batches * batch_pairs]
    return perm.reshape(num_batches, batch_pairs).astype(jnp.int32)


def epoch_shuffle(
    pairs: jax.Array,
    key: jax.Array,
    num_pairs: int,
    num_batches: int,
    batch_pairs: int,
    mode: str,
    enabled: bool = True,
) -> jax.Array:
    """Per-epoch corpus shuffle for jitted epoch loops (shared by the SGNS
    and CBOW/HS trainers).  Returns an array the epoch scan slices
    sequentially (length ≥ num_batches·batch_pairs rows).

    Random row gathers are issue-bound on TPU (docs/PERF_NOTES.md), so the
    default ``"offset"`` mode never does one: the corpus is host-shuffled
    once at trainer construction, and each epoch applies a random circular
    roll plus a permutation of fixed 512-pair blocks — block gathers stay
    coalesced (a stream pass), while re-mixing batch composition every
    epoch.  ``"full"`` is the reference's exact per-epoch row permutation
    (``src/gene2vec.py:80``) at the price of an N-row random gather.
    """
    if not enabled:
        return pairs
    if mode == "full":
        perm = epoch_permutation(key, num_pairs, batch_pairs)
        return pairs[perm.reshape(-1)]
    if mode != "offset":
        raise ValueError(f"unknown shuffle_mode {mode!r}")
    off_key, blk_key = jax.random.split(key)
    offset = jax.random.randint(off_key, (), 0, num_pairs)
    rolled = jnp.roll(pairs, offset, axis=0)
    span = num_batches * batch_pairs
    block = 512 if span % 512 == 0 else batch_pairs
    nblocks = span // block
    blocks = rolled[:span].reshape(nblocks, block, 2)
    return blocks[jax.random.permutation(blk_key, nblocks)].reshape(span, 2)


def host_preshuffle(corpus: "PairCorpus", seed: int) -> "PairCorpus":
    """One-time host-side shuffle backing ``epoch_shuffle``'s offset mode —
    the analogue of the reference's pre-training ``random.shuffle``
    (``src/gene2vec.py:52``)."""
    rng = np.random.RandomState(seed)
    return PairCorpus(corpus.vocab, corpus.pairs[rng.permutation(corpus.num_pairs)])
