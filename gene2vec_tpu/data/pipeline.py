"""Pair-stream pipeline: the corpus lives in HBM, shuffling happens on device.

The reference keeps the whole corpus as a Python list of 2-element lists and
reshuffles it with ``random.shuffle`` every iteration (``src/gene2vec.py:32-52,80``)
— hundreds of millions of Python objects.  Here the encoded corpus is one
(N, 2) int32 device array; an epoch's shuffle is a ``jax.random.permutation``
folded into the jitted epoch scan, so the host never touches pair data after
the initial upload.

Batching drops the ragged tail (< batch_pairs pairs) of each epoch — with the
per-epoch reshuffle every pair still gets seen in expectation, and static
shapes are what keep XLA from recompiling.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.io.vocab import Vocab


class PairCorpus:
    """Encoded pair corpus + vocab, with device-resident batching helpers."""

    def __init__(self, vocab: Vocab, pairs: np.ndarray):
        pairs = np.asarray(pairs, dtype=np.int32)
        if pairs.ndim != 2 or pairs.shape[1] != 2:
            raise ValueError(f"pairs must be (N, 2), got {pairs.shape}")
        self.vocab = vocab
        self.pairs = pairs

    @property
    def num_pairs(self) -> int:
        return int(self.pairs.shape[0])

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def num_batches(self, batch_pairs: int) -> int:
        return self.num_pairs // batch_pairs

    def device_pairs(self, sharding: Optional[jax.sharding.Sharding] = None) -> jax.Array:
        """Upload the corpus once; optionally sharded over the data axis."""
        if sharding is not None:
            return jax.device_put(self.pairs, sharding)
        return jnp.asarray(self.pairs)

    def pad_to_multiple(self, multiple: int) -> "PairCorpus":
        """Pad (by wrapping around) so num_pairs is divisible by ``multiple``
        — needed to shard the corpus evenly across data-parallel devices."""
        n = self.num_pairs
        rem = n % multiple
        if rem == 0:
            return self
        extra = self.pairs[: multiple - rem]
        return PairCorpus(self.vocab, np.concatenate([self.pairs, extra], axis=0))

    def process_shard(
        self, index: Optional[int] = None, count: Optional[int] = None
    ) -> "PairCorpus":
        """This host's strided shard of the corpus for multi-host SPMD runs
        (docs/DISTRIBUTED.md): every host reads the same files, keeps rows
        ``index::count``, and feeds only its shard of the global batch.
        Strided (not blocked) so hosts' shards interleave the corpus order
        and the per-epoch shuffle stays well-mixed globally.  Defaults to
        ``jax.process_index()``/``jax.process_count()``; identity on a
        single-process run.  Vocab (built from the FULL corpus) is shared —
        call before any per-host padding.

        Every shard is trimmed to exactly ``num_pairs // count`` rows: the
        trainer derives ``num_batches`` (and the small-corpus batch shrink)
        from its *local* shard, so hosts whose shards differed by one row
        could compile different epoch step counts and deadlock the SPMD
        collectives.  Dropping < count tail rows is harmless — the per-epoch
        reshuffle already drops ragged batch tails by design."""
        if index is None:
            index = jax.process_index()
        if count is None:
            count = jax.process_count()
        if count < 1:
            # a buggy launcher (unset env parsed as 0) must not silently
            # feed every host the full corpus
            raise ValueError(f"process count must be >= 1, got {count}")
        if not 0 <= index < count:
            raise ValueError(f"process index {index} not in [0, {count})")
        if count == 1:
            return self
        per_host = self.num_pairs // count
        return PairCorpus(self.vocab, self.pairs[index::count][:per_host])

    def host_batches(
        self, batch_pairs: int, rng: np.random.Generator, shuffle: bool = True
    ) -> Iterator[np.ndarray]:
        """Host-side batch iterator (CPU oracle paths / tests)."""
        order = (
            rng.permutation(self.num_pairs) if shuffle else np.arange(self.num_pairs)
        )
        for b in range(self.num_batches(batch_pairs)):
            yield self.pairs[order[b * batch_pairs : (b + 1) * batch_pairs]]


def epoch_permutation(key: jax.Array, num_pairs: int, batch_pairs: int) -> jax.Array:
    """(num_batches, batch_pairs) int32 shuffled index matrix for one epoch —
    the device-side equivalent of the reference's per-iteration
    ``random.shuffle(gene_pairs)`` (``src/gene2vec.py:80``)."""
    num_batches = num_pairs // batch_pairs
    perm = jax.random.permutation(key, num_pairs)[: num_batches * batch_pairs]
    return perm.reshape(num_batches, batch_pairs).astype(jnp.int32)


def epoch_shuffle(
    pairs: jax.Array,
    key: jax.Array,
    num_pairs: int,
    num_batches: int,
    batch_pairs: int,
    mode: str,
    enabled: bool = True,
) -> jax.Array:
    """Per-epoch corpus shuffle for jitted epoch loops (shared by the SGNS
    and CBOW/HS trainers).  Returns an array the epoch scan slices
    sequentially (length ≥ num_batches·batch_pairs rows).

    Random row gathers are issue-bound on TPU (docs/PERF_NOTES.md), so the
    default ``"offset"`` mode never does one: the corpus is host-shuffled
    once at trainer construction, and each epoch applies a random circular
    roll plus a permutation of fixed 512-pair blocks — block gathers stay
    coalesced (a stream pass), while re-mixing batch composition every
    epoch.  ``"full"`` is the reference's exact per-epoch row permutation
    (``src/gene2vec.py:80``) at the price of an N-row random gather.
    """
    if not enabled:
        return pairs
    if mode == "full":
        perm = epoch_permutation(key, num_pairs, batch_pairs)
        return pairs[perm.reshape(-1)]
    if mode != "offset":
        raise ValueError(f"unknown shuffle_mode {mode!r}")
    off_key, blk_key = jax.random.split(key)
    offset = jax.random.randint(off_key, (), 0, num_pairs)
    rolled = jnp.roll(pairs, offset, axis=0)
    span = num_batches * batch_pairs
    block = 512 if span % 512 == 0 else batch_pairs
    nblocks = span // block
    blocks = rolled[:span].reshape(nblocks, block, 2)
    return blocks[jax.random.permutation(blk_key, nblocks)].reshape(span, 2)


def pool_class_pairs(n_classes: int):
    """Canonical (class_a, class_b) per pool, a <= b, lexicographic — the
    pool order :func:`segment_corpus_by_head` emits and
    ``sgns/step.py:_pool_class_pairs`` consumes."""
    return [(a, b) for a in range(n_classes) for b in range(a, n_classes)]


def segment_corpus_by_head(
    pairs: np.ndarray, head, batch_pairs: int, multiple: int = 1
) -> Tuple[Tuple[np.ndarray, ...], Tuple[int, ...]]:
    """Host-side class segmentation backing the dense-slab positive path
    (``sgns/step.py`` rounds 4-5): classify each token by which frequency
    band it falls in (``head`` is one boundary — classes head/tail — or an
    ascending boundary sequence, e.g. ``(512, 4608)`` for
    head/mid/tail), split the corpus into one pool per unordered class
    pair (pairs canonicalized lower-class-token-first, a no-op under
    both-direction example emission; :func:`pool_class_pairs` order), and
    compute static per-batch quotas summing to ``batch_pairs`` so every
    batch carries the corpus's class mix at fixed segment offsets.  The
    step can then gather/scatter slab-class rows as one-hot MXU matmuls
    over the contiguous ``table[lo:hi]`` slabs.

    Quotas are floors of each pool's share of ``num_batches`` batches;
    rounding leftovers are settled deterministically (largest-pool
    decrement / largest-leftover increment, the latter wrap-padding its
    pool by < num_batches rows — the same wrap device ``pad_to_multiple``
    uses).  Each pool keeps ALL its rows (>= quota * num_batches): the
    per-epoch roll in :func:`segmented_epoch_shuffle` cycles which rows
    fall into the epoch's span, so no pair is dropped permanently.

    ``multiple`` forces every quota to a multiple of it (the data-parallel
    device count: each device block of a batch carries quota/multiple rows
    of each class, so the per-device segment layout is uniform).
    """
    if batch_pairs <= 0 or pairs.shape[0] < batch_pairs:
        raise ValueError(
            f"cannot segment {pairs.shape[0]} pairs into "
            f"batches of {batch_pairs}"
        )
    if multiple < 1 or batch_pairs % multiple:
        raise ValueError(
            f"batch_pairs={batch_pairs} must be a positive multiple of "
            f"multiple={multiple}"
        )
    boundaries = np.atleast_1d(np.asarray(head, dtype=np.int64))
    if boundaries.ndim != 1 or np.any(np.diff(boundaries) <= 0):
        raise ValueError(f"head boundaries must be ascending, got {head}")
    n_classes = len(boundaries) + 1
    num_batches = pairs.shape[0] // batch_pairs
    # token class = number of boundaries <= token (0 = hottest band)
    cls = np.searchsorted(boundaries, pairs, side="right")
    swap = cls[:, 0] > cls[:, 1]
    canon = pairs.copy()
    canon[swap] = canon[swap][:, ::-1]
    cls.sort(axis=1)
    pools = [
        canon[(cls[:, 0] == a) & (cls[:, 1] == b)]
        for a, b in pool_class_pairs(n_classes)
    ]

    # every non-empty class gets quota >= multiple: a pool smaller than
    # one row per batch(-block) would otherwise round to 0 and its pairs
    # would NEVER train (the roll cycles within a pool, not across pools)
    m = multiple
    floors = [m if len(p) else 0 for p in pools]
    if sum(floors) > batch_pairs:
        raise ValueError(
            f"batch_pairs={batch_pairs} is smaller than m x the number of "
            f"non-empty head classes ({sum(floors)})"
        )
    quotas = [
        max(len(p) // num_batches // m * m, f)
        for p, f in zip(pools, floors)
    ]
    while sum(quotas) > batch_pairs:
        # decrement the largest quota that stays above its floor
        c = int(
            np.argmax([q if q > f else -1 for q, f in zip(quotas, floors)])
        )
        quotas[c] -= m
    while sum(quotas) < batch_pairs:
        leftover = [
            len(p) - q * num_batches for p, q in zip(pools, quotas)
        ]
        quotas[int(np.argmax(leftover))] += m
    for c, (pool, q) in enumerate(zip(pools, quotas)):
        need = q * num_batches
        if 0 < len(pool) < need:
            # wrap-pad: tile the pool to the quota (a pool under one row
            # per batch repeats; mild oversampling of a tiny class beats
            # dropping it)
            reps = -(-need // len(pool))
            pool = np.concatenate([pool] * reps, axis=0)[:need]
        rem = len(pool) % m
        if rem:
            # row counts also wrap-pad to the multiple HERE — not at
            # device_put — so a pos_layout_shards-pinned single-device
            # reference shuffles the exact same pool (same num_pairs,
            # same roll range) as the sharded run it is compared against
            pool = np.concatenate([pool, pool[: m - rem]], axis=0)
        pools[c] = pool
    return tuple(pools), tuple(quotas)


def segment_corpus_by_head_multihost(
    pairs_full: np.ndarray,
    head,
    batch_pairs: int,
    multiple: int,
    index: int,
    count: int,
):
    """Multi-host dense-head segmentation: every host calls this with the
    SAME full corpus (the documented flow — each host reads all pair
    files before :meth:`PairCorpus.process_shard`) and receives its LOCAL
    shard of each class pool plus the GLOBAL quotas.

    Everything is a deterministic function of the full corpus, so all
    hosts compute identical quotas and identical per-host pool lengths —
    the property that makes the static batch layout safe under SPMD
    (mismatched quotas would compile different programs and deadlock the
    collectives; docs/DISTRIBUTED.md).

    Construction: classify + quota on the full corpus exactly as the
    single-host :func:`segment_corpus_by_head` (``multiple`` = the global
    data-axis size), then give each host the strided shard
    ``pool[index::count]`` adjusted to the agreed length ``L_c`` =
    max(floor-share, coverage need), rounded to the per-host device
    multiple — trimming or wrap-padding the local shard as needed.
    Returns (local_pools, quotas, num_batches).

    Trimming note: unlike the single-host path (whose per-epoch roll
    eventually reaches every pool row), the ``local[:target]`` trim drops
    up to ~one device-multiple of rows per pool per host PERMANENTLY —
    the epoch roll cycles within the trimmed shard.  This is the same
    order of loss as :meth:`PairCorpus.process_shard`'s documented
    ``num_pairs // count`` trim (< count + multiple rows out of millions)
    and is accepted for the same reason: equal per-host lengths are what
    keep every host compiling the same program (docs/DISTRIBUTED.md).
    """
    if count < 1 or not 0 <= index < count:
        raise ValueError(f"bad process coordinates {index}/{count}")
    if multiple % count:
        raise ValueError(
            f"device-block count {multiple} must be divisible by the "
            f"process count {count} (equal devices per host)"
        )
    pools, quotas = segment_corpus_by_head(
        pairs_full, head, batch_pairs, multiple=multiple
    )
    num_batches = pairs_full.shape[0] // batch_pairs
    lm = max(multiple // count, 1)  # per-host device multiple
    local_pools = []
    for pool, q in zip(pools, quotas):
        if len(pool) == 0:
            local_pools.append(pool)
            continue
        share = len(pool) // count // lm * lm
        need = -(-q * num_batches // count)  # ceil coverage per host
        target = max(share, -(-need // lm) * lm)
        local = pool[index::count]
        if len(local) == 0:
            # a tiny pool whose strided rows all landed on other hosts:
            # borrow from the (globally known) pool — host LENGTHS must
            # agree, host contents need not
            local = pool
        if len(local) < target:
            reps = -(-target // len(local))
            local = np.concatenate([local] * reps, axis=0)
        local_pools.append(local[:target])
    return tuple(local_pools), quotas, num_batches


def segmented_epoch_shuffle(
    pools, key: jax.Array, quotas, num_batches: int, mode: str,
    enabled: bool = True,
):
    """Per-epoch shuffle for class-segmented corpora: each pool shuffles
    independently (same roll + block-permutation machinery as
    :func:`epoch_shuffle`), then batch ``b`` is the concatenation of row
    range ``[b*q_c, (b+1)*q_c)`` from each pool — static [HH|HT|TT]
    segment layout every batch."""
    keys = jax.random.split(key, len(pools))
    return tuple(
        # zero-quota pools contribute no rows to any batch; epoch_shuffle
        # ("full" mode) would divide by batch_pairs=0
        pool[:0]
        if q == 0
        else epoch_shuffle(
            pool, k, pool.shape[0], num_batches, q, mode, enabled=enabled
        )
        for pool, k, q in zip(pools, keys, quotas)
    )


def host_preshuffle(corpus: "PairCorpus", seed: int) -> "PairCorpus":
    """One-time host-side shuffle backing ``epoch_shuffle``'s offset mode —
    the analogue of the reference's pre-training ``random.shuffle``
    (``src/gene2vec.py:52``)."""
    rng = np.random.RandomState(seed)
    return PairCorpus(corpus.vocab, corpus.pairs[rng.permutation(corpus.num_pairs)])
