"""Chunk-committed batch artifacts with a durable, CRC-stamped cursor.

A batch job's output grows as ONE append-only data file
(``<job_dir>/DATA.bin``) under the same commit protocol as the loop
ingest corpus (loop/ingest.py; docs/RESILIENCE.md failure model — the
writer can die at ANY instruction):

1. **Recover** — if ``DATA.bin`` is longer than the cursor's committed
   byte offset, the previous worker died mid-append: truncate back to
   the committed prefix (whose rolling CRC32 the cursor stamps, so
   post-commit rot is detected too, not just torn tails).
2. **Append** — one chunk's bytes are appended and fsync'd.
3. **Commit** — a new ``CURSOR.json`` (chunk count, byte offset,
   rolling CRC32 — self-CRC-stamped, previous cursor kept as
   ``CURSOR.prev.json``) is written atomically LAST.

Because every job type packs its output **per record** (per graph row,
per pair, per export line) the committed prefix is a pure function of
how many records are done — chunk boundaries never leak into the bytes,
so a SIGKILL'd-and-resumed build produces a final artifact bit-identical
to an uninterrupted control no matter where it was killed.

Completion is the atomic write of ``ARTIFACT.json`` (the manifest: full
data CRC + job metadata).  A reader trusts ``DATA.bin`` only through a
manifest that verifies.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from gene2vec_tpu.resilience import snapshot as snap

CURSOR_SCHEMA = "gene2vec-tpu/batch-artifact-cursor/v1"
MANIFEST_SCHEMA = "gene2vec-tpu/batch-artifact/v1"
DATA_NAME = "DATA.bin"
CURSOR_NAME = "CURSOR.json"
CURSOR_PREV_NAME = "CURSOR.prev.json"
MANIFEST_NAME = "ARTIFACT.json"
TOKENS_NAME = "TOKENS.txt"


def _payload_crc(doc: Dict) -> int:
    body = {k: v for k, v in sorted(doc.items()) if k != "cursor_crc32"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True).encode("utf-8")
    ) & 0xFFFFFFFF


class ChunkedArtifact:
    """The commit-protocol writer/reader for one job's output dir."""

    def __init__(self, job_dir: str):
        self.job_dir = job_dir
        os.makedirs(job_dir, exist_ok=True)
        self.data_path = os.path.join(job_dir, DATA_NAME)
        self._cursor = self._load_cursor()
        self._recover()

    # -- cursor ----------------------------------------------------------

    def _empty_cursor(self) -> Dict:
        return {
            "schema": CURSOR_SCHEMA,
            "chunks_done": 0,
            "records_done": 0,
            "data_bytes": 0,
            "data_crc32": 0,
        }

    def _load_cursor(self) -> Dict:
        for name in (CURSOR_NAME, CURSOR_PREV_NAME):
            path = os.path.join(self.job_dir, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(doc, dict):
                continue
            if doc.get("cursor_crc32") != _payload_crc(doc):
                continue
            return doc
        if (
            os.path.exists(self.data_path)
            and os.path.getsize(self.data_path) > 0
        ):
            raise IOError(
                f"{self.job_dir}: committed data present but no readable "
                "self-CRC-valid cursor (both CURSOR.json and "
                "CURSOR.prev.json lost/rotted) — refusing to truncate "
                "the whole artifact to a fresh cursor"
            )
        return self._empty_cursor()

    def _write_cursor(self, doc: Dict) -> None:
        doc = dict(doc)
        doc["cursor_crc32"] = _payload_crc(doc)
        cur = os.path.join(self.job_dir, CURSOR_NAME)
        if os.path.exists(cur):
            # keep the last good commit: a cursor torn by post-write rot
            # falls back one chunk instead of losing the whole offset
            with open(cur, "rb") as f:
                snap.atomic_write_bytes(
                    os.path.join(self.job_dir, CURSOR_PREV_NAME), f.read()
                )
        snap.atomic_write_json(cur, doc)
        self._cursor = doc

    def _recover(self) -> None:
        """Enforce the committed prefix: truncate a torn append, verify
        the prefix CRC (training-grade discipline — resuming on rotted
        bytes would silently corrupt the final artifact)."""
        committed = int(self._cursor.get("data_bytes", 0))
        size = (
            os.path.getsize(self.data_path)
            if os.path.exists(self.data_path) else 0
        )
        if size > committed:
            with open(self.data_path, "r+b") as f:
                f.truncate(committed)
                f.flush()
                os.fsync(f.fileno())
        elif size < committed:
            raise IOError(
                f"{self.data_path}: {size} bytes on disk but the cursor "
                f"committed {committed} — data truncated after commit"
            )
        if committed:
            crc = 0
            with open(self.data_path, "rb") as f:
                while True:
                    blob = f.read(1 << 20)
                    if not blob:
                        break
                    crc = zlib.crc32(blob, crc)
            if (crc & 0xFFFFFFFF) != int(self._cursor.get("data_crc32", 0)):
                raise IOError(
                    f"{self.data_path}: committed prefix CRC mismatch — "
                    "the artifact rotted after commit; restart the job "
                    "in a fresh dir"
                )

    # -- progress facts ---------------------------------------------------

    @property
    def chunks_done(self) -> int:
        return int(self._cursor.get("chunks_done", 0))

    @property
    def records_done(self) -> int:
        return int(self._cursor.get("records_done", 0))

    @property
    def data_bytes(self) -> int:
        return int(self._cursor.get("data_bytes", 0))

    # -- the commit protocol ----------------------------------------------

    def append_chunk(self, data: bytes, records: int) -> None:
        """Append one chunk's record bytes and commit the cursor LAST.
        A SIGKILL anywhere before the commit leaves the chunk torn; the
        next open truncates and the runner redoes it."""
        if os.path.exists(os.path.join(self.job_dir, MANIFEST_NAME)):
            raise IOError(f"{self.job_dir}: artifact already finalized")
        with open(self.data_path, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        snap.fsync_dir(self.job_dir)
        self._write_cursor({
            "schema": CURSOR_SCHEMA,
            "chunks_done": self.chunks_done + 1,
            "records_done": self.records_done + int(records),
            "data_bytes": self.data_bytes + len(data),
            "data_crc32": zlib.crc32(
                data, int(self._cursor.get("data_crc32", 0))
            ) & 0xFFFFFFFF,
        })

    def write_tokens(self, tokens) -> None:
        """The artifact's gene-name sidecar (one per line, vocab order)
        — written atomically before the first chunk so a standalone
        reader (eval/, Dash) can map packed row ids back to genes."""
        snap.atomic_write_bytes(
            os.path.join(self.job_dir, TOKENS_NAME),
            ("\n".join(str(t) for t in tokens) + "\n").encode("utf-8"),
        )

    def finalize(self, meta: Dict) -> str:
        """Atomically commit the completion manifest.  Idempotent — a
        resumed job that was killed between the last chunk and the
        manifest just rewrites the same document."""
        path = os.path.join(self.job_dir, MANIFEST_NAME)
        doc = {
            "schema": MANIFEST_SCHEMA,
            "chunks": self.chunks_done,
            "records": self.records_done,
            "data_bytes": self.data_bytes,
            "data_crc32": int(self._cursor.get("data_crc32", 0)),
            "meta": dict(meta),
        }
        snap.atomic_write_json(path, doc)
        return path

    # -- the reader side --------------------------------------------------

    def manifest(self) -> Optional[Dict]:
        path = os.path.join(self.job_dir, MANIFEST_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def verify(self) -> bool:
        """Finalized AND the data bytes still match the manifest CRC."""
        doc = self.manifest()
        if doc is None:
            return False
        try:
            if os.path.getsize(self.data_path) != int(doc["data_bytes"]):
                return False
            return snap.crc32_file(self.data_path) == int(doc["data_crc32"])
        except (OSError, KeyError, ValueError):
            return False


def write_fetched_artifact(
    job_dir: str,
    data: bytes,
    meta: Dict,
    chunks: int,
    records: int,
    data_crc32: int,
    tokens_bytes: Optional[bytes] = None,
) -> None:
    """Materialize an artifact dir from HTTP-fetched parts
    (``/v1/jobs/<id>/artifact``), byte-identical and fully loadable:
    the reassembled data must match the manifest CRC or this refuses
    to write anything."""
    got = zlib.crc32(data) & 0xFFFFFFFF
    if got != int(data_crc32):
        raise IOError(
            f"fetched data CRC {got} != manifest {data_crc32} "
            "(torn/reordered pages?)"
        )
    os.makedirs(job_dir, exist_ok=True)
    snap.atomic_write_bytes(os.path.join(job_dir, DATA_NAME), data)
    if tokens_bytes is not None:
        snap.atomic_write_bytes(
            os.path.join(job_dir, TOKENS_NAME), tokens_bytes
        )
    cursor = {
        "schema": CURSOR_SCHEMA,
        "chunks_done": int(chunks),
        "records_done": int(records),
        "data_bytes": len(data),
        "data_crc32": int(data_crc32),
    }
    cursor["cursor_crc32"] = _payload_crc(cursor)
    snap.atomic_write_json(os.path.join(job_dir, CURSOR_NAME), cursor)
    snap.atomic_write_json(os.path.join(job_dir, MANIFEST_NAME), {
        "schema": MANIFEST_SCHEMA,
        "chunks": int(chunks),
        "records": int(records),
        "data_bytes": len(data),
        "data_crc32": int(data_crc32),
        "meta": dict(meta),
    })


# -- kNN-graph record packing -------------------------------------------------
#
# One record per vocab row: k int32 global neighbor row ids then k
# float32 scores, little-endian, row-major.  Chunk boundaries never
# appear in the bytes, so resumed and uninterrupted builds are
# bit-identical by construction.


def pack_graph_rows(ids: np.ndarray, scores: np.ndarray) -> bytes:
    ids = np.ascontiguousarray(ids, dtype="<i4")
    scores = np.ascontiguousarray(scores, dtype="<f4")
    if ids.shape != scores.shape or ids.ndim != 2:
        raise ValueError(
            f"ids/scores must be matching (n, k) arrays, got "
            f"{ids.shape} vs {scores.shape}"
        )
    n, k = ids.shape
    out = np.empty((n, 2 * k), dtype="<i4")
    out[:, :k] = ids
    out[:, k:] = scores.view("<i4")
    return out.tobytes()


def unpack_graph(
    data: bytes, k: int
) -> Tuple[np.ndarray, np.ndarray]:
    rec = np.frombuffer(data, dtype="<i4").reshape(-1, 2 * k)
    ids = rec[:, :k].astype(np.int32)
    scores = rec[:, k:].copy().view("<f4").astype(np.float32)
    return ids, scores


def load_graph(
    job_dir: str,
) -> Tuple[List[str], np.ndarray, np.ndarray, Dict]:
    """(tokens, neighbor row ids [V, k], scores [V, k], meta) from a
    FINALIZED ``knn_graph`` artifact dir — the precomputed-graph input
    to the intrinsic eval and the Dash neighbor-view fallback."""
    art = ChunkedArtifact(job_dir)
    doc = art.manifest()
    if doc is None:
        raise IOError(
            f"{job_dir}: no ARTIFACT.json — the graph build has not "
            "completed (or this is not a batch artifact dir)"
        )
    if not art.verify():
        raise IOError(f"{job_dir}: artifact data fails manifest CRC")
    meta = doc.get("meta", {})
    if meta.get("type") != "knn_graph":
        raise IOError(
            f"{job_dir}: artifact type {meta.get('type')!r} is not a "
            "knn_graph"
        )
    k = int(meta["k"])
    with open(art.data_path, "rb") as f:
        ids, scores = unpack_graph(f.read(), k)
    tokens_path = os.path.join(job_dir, TOKENS_NAME)
    with open(tokens_path, "r", encoding="utf-8") as f:
        tokens = [ln.rstrip("\n") for ln in f if ln.rstrip("\n")]
    if len(tokens) != ids.shape[0]:
        raise IOError(
            f"{job_dir}: {len(tokens)} tokens but {ids.shape[0]} graph "
            "rows"
        )
    return tokens, ids, scores, meta
