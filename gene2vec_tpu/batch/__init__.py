"""Offline analytics plane: resumable batch jobs on the serve fleet.

Three job types run at background priority against live serving state
(docs/BATCH.md):

* ``knn_graph`` — the full-vocab kNN graph: every row as a query
  through the retrieval engine (exact-rescored in quant/ivf modes),
  packed per-row so the final artifact is bit-identical no matter how
  the build was chunked or how many times it was killed and resumed;
* ``pair_scores`` — bulk GGIPNN interaction scoring over a candidate
  pair list, one text line per pair;
* ``export`` — a streaming word2vec-format embedding export, chunked
  through the same commit protocol.

The plane is three layers: :mod:`artifact` (CRC'd-cursor chunk store,
the resilience commit protocol), :mod:`runner` (job loops generic over
a query backend — in-process engine, batcher lane, or shard-group
scatter), and :mod:`jobs` (the journal + worker + ``/v1/jobs``
lifecycle surface mounted on the serve front doors).
"""

from gene2vec_tpu.batch.artifact import ChunkedArtifact, load_graph
from gene2vec_tpu.batch.jobs import JobManager, JobSpec, dispatch_jobs

__all__ = [
    "ChunkedArtifact",
    "JobManager",
    "JobSpec",
    "dispatch_jobs",
    "load_graph",
]
