"""Batch-job runners: chunk loops generic over a query backend.

One runner per job type (kNN graph, bulk pair scoring, streaming
export), each writing through :class:`batch.artifact.ChunkedArtifact`'s
commit protocol so a SIGKILL anywhere resumes to a bit-identical final
artifact.  The backend abstracts WHERE queries execute:

* :class:`EngineBackend` — in-process model + engine (``cli.batch``
  local mode, bench oracles);
* :class:`BatcherBackend` — a live replica's micro-batcher, every
  query submitted on the low-weight ``batch`` tenant lane
  (serve/tenancy.py) so interactive traffic wins the weighted-fair
  dequeue; queue-full rejections back off instead of erroring — the
  deadline-aware admission that protects the interactive SLO;
* :class:`ShardGroupBackend` — the fleet front door's scatter-gather
  (serve/shardgroup.py): full-vocab queries fan out across the shard
  grid, degraded answers (a shard group mid-failover) retry with
  backoff rather than poisoning the artifact.

Determinism contract: record bytes are a pure function of the served
model (scores rounded to 6 decimals exactly like the interactive
surface), so control and resumed builds against the same iteration
compare equal byte-for-byte.  A hot swap mid-job changes that function;
runners pin the iteration at job start and fail loudly on drift.
"""

from __future__ import annotations

import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.batch.artifact import ChunkedArtifact, pack_graph_rows
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.serve.tenancy import BATCH_TENANT

__all__ = [
    "BatcherBackend",
    "ChunkFailed",
    "ClientBackend",
    "EngineBackend",
    "JobCancelled",
    "Pacer",
    "ShardGroupBackend",
    "run_job",
]

#: generous per-query deadline for batch-lane requests: the lane is
#: background priority, so queue time under interactive load is the
#: POINT, not a failure
_BATCH_TIMEOUT_S = 60.0


class JobCancelled(Exception):
    """The job's cancel flag was observed between chunks."""


class ChunkFailed(Exception):
    """One chunk kept failing after every retry (backend down/degraded
    past the retry budget, or answers that cannot be mapped)."""


class Pacer:
    """Background-priority pacing: before each chunk, yield while the
    interactive plane is under pressure (``guard()`` above
    ``guard_max``), then pay a duty-cycle sleep proportional to the
    last chunk's wall time so batch work never monopolizes the
    backend even when the queue is empty.

    ``duty`` is the fraction of wall time the job may consume: 1.0 =
    no idle gap, 0.5 = sleep as long as each chunk took."""

    def __init__(
        self,
        guard: Optional[Callable[[], float]] = None,
        guard_max: float = 0.5,
        duty: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.guard = guard
        self.guard_max = float(guard_max)
        self.duty = min(1.0, max(0.05, float(duty)))
        self._clock = clock
        self._sleep = sleep
        self.yielded_s = 0.0

    def wait(self, last_chunk_s: float,
             should_stop: Optional[Callable[[], bool]] = None) -> None:
        t0 = self._clock()
        if self.duty < 1.0 and last_chunk_s > 0:
            gap = last_chunk_s * (1.0 - self.duty) / self.duty
            self._sleep(min(gap, 5.0))
        backoff = 0.05
        while self.guard is not None and self.guard() > self.guard_max:
            if should_stop is not None and should_stop():
                break
            self._sleep(backoff)
            backoff = min(backoff * 2, 1.0)
        self.yielded_s += self._clock() - t0


def _retrying(fn: Callable, attempts: int = 5,
              sleep: Callable[[float], None] = time.sleep):
    """Retry a chunk computation with exponential backoff — a shard
    failover or a saturated queue is a pause, not a job failure."""
    delay = 0.25
    last: Optional[Exception] = None
    for _ in range(max(1, attempts)):
        try:
            return fn()
        except (ChunkFailed, OSError) as e:
            last = e
            sleep(delay)
            delay = min(delay * 2, 8.0)
    raise ChunkFailed(
        f"chunk failed after {attempts} attempts: {last}"
    ) from last


# -- backends -----------------------------------------------------------------


class EngineBackend:
    """Direct model + engine compute (no serving stack): ``cli.batch``
    local mode and the bench's throughput/oracle measurements."""

    def __init__(self, model, engine, ggipnn_checkpoint: Optional[str] = None):
        self.model = model
        self.engine = engine
        self._ggipnn_checkpoint = ggipnn_checkpoint
        self._scorer = None

    @property
    def tokens(self) -> Sequence[str]:
        return self.model.tokens

    @property
    def dim(self) -> int:
        return self.model.dim

    @property
    def iteration(self) -> int:
        return self.model.iteration

    def pressure(self) -> float:
        return 0.0

    def knn_rows(self, start: int, n: int, k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
        model = self.model
        kq = min(k + 1, len(model))
        queries = np.asarray(model.emb[start:start + n], dtype=np.float32)
        scores, rows = self.engine.topk_rows(model, queries, kq)
        return _drop_self(
            np.asarray(rows), np.asarray(scores), start, k
        )

    def pair_scores(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        if self._scorer is None:
            from gene2vec_tpu.serve.interaction import InteractionScorer

            self._scorer = InteractionScorer(
                self.model, checkpoint_path=self._ggipnn_checkpoint
            )
        return [
            round(float(s), 6)
            for s in self._scorer.score([tuple(p) for p in pairs])
        ]

    def vector_rows(self, start: int, n: int) -> List[List[float]]:
        return [
            [float(v) for v in row]
            for row in self.model.emb[start:start + n]
        ]


def _drop_self(rows: np.ndarray, scores: np.ndarray, start: int, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """(n, k+1) engine answers -> (n, k) neighbor records with the
    query's own row removed (the interactive /v1/similar contract);
    scores rounded to 6 decimals exactly like the serve surface."""
    n = rows.shape[0]
    out_ids = np.empty((n, k), dtype=np.int32)
    out_scores = np.empty((n, k), dtype=np.float32)
    for i in range(n):
        self_row = start + i
        keep = [j for j in range(rows.shape[1])
                if int(rows[i, j]) != self_row][:k]
        if len(keep) < k:
            raise ChunkFailed(
                f"row {self_row}: only {len(keep)} non-self neighbors "
                f"returned (need k={k}; vocab too small?)"
            )
        out_ids[i] = rows[i, keep]
        out_scores[i] = np.asarray(
            [round(float(scores[i, j]), 6) for j in keep],
            dtype=np.float32,
        )
    return out_ids, out_scores


class BatcherBackend:
    """A live :class:`serve.server.ServeApp`'s query plane, entered on
    the ``batch`` tenant lane.  Every kNN query is one batcher item —
    the FairQueue interleaves them under interactive lanes at
    ``batch_weight``, and queue-full rejections back off (admission is
    pressure-aware by construction)."""

    def __init__(self, app):
        self.app = app
        self._model = app.registry.model

    @property
    def tokens(self) -> Sequence[str]:
        return self._model.tokens

    @property
    def dim(self) -> int:
        return self._model.dim

    @property
    def iteration(self) -> int:
        return self._model.iteration

    def pressure(self) -> float:
        depth = len(self.app.batcher._q)
        return depth / max(1, self.app.config.max_queue)

    def knn_rows(self, start: int, n: int, k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
        from gene2vec_tpu.serve.batcher import (
            DeadlineExceeded,
            RejectedError,
        )

        model = self._model
        genes = model.tokens[start:start + n]
        tickets = []
        for g in genes:
            q = {"gene": g, "k": k}
            while True:
                try:
                    tickets.append(self.app.batcher.submit_async(
                        q, k,
                        cache_key=(model.version, "similar", g, k),
                        timeout_s=_BATCH_TIMEOUT_S,
                        tenant=BATCH_TENANT,
                    ))
                    break
                except RejectedError:
                    # the bounded queue is full of interactive work:
                    # yield, never displace it (docs/BATCH.md
                    # #priority-tier-contract)
                    time.sleep(0.05)
        ids = np.empty((n, k), dtype=np.int32)
        scores = np.empty((n, k), dtype=np.float32)
        index = model.index
        for i, t in enumerate(tickets):
            try:
                r = t.get()
            except DeadlineExceeded as e:
                raise ChunkFailed(str(e)) from e
            if "error" in r:
                raise ChunkFailed(r["error"])
            hits = r["neighbors"][:k]
            if len(hits) < k:
                raise ChunkFailed(
                    f"gene {genes[i]!r}: {len(hits)} neighbors < k={k}"
                )
            for j, h in enumerate(hits):
                row = index.get(h["gene"])
                if row is None:
                    raise ChunkFailed(
                        f"neighbor {h['gene']!r} not in served vocab "
                        "(swap mid-chunk?)"
                    )
                ids[i, j] = row
                scores[i, j] = h["score"]
        return ids, scores

    def pair_scores(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        scorer = self.app._get_scorer(self._model)
        try:
            raw = scorer.score([tuple(p) for p in pairs])
        except KeyError as e:
            raise ChunkFailed(f"unknown gene {e.args[0]!r}") from e
        return [round(float(s), 6) for s in raw]

    def vector_rows(self, start: int, n: int) -> List[List[float]]:
        return [
            [float(v) for v in row]
            for row in self._model.emb[start:start + n]
        ]


class ClientBackend:
    """An unsharded fleet front door's replica pool, queried through
    its :class:`serve.client.ResilientClient` with ``X-Tenant: batch``
    — each replica's own FairQueue then drains the job's queries at
    the batch weight, so front-door jobs inherit the same priority
    contract as in-process ones.  ``max_queries`` bounds each request
    to the replicas' per-request query cap."""

    _HEADERS = {"X-Tenant": BATCH_TENANT}

    def __init__(self, client, max_queries: int = 64):
        self.client = client
        self._sub = int(max_queries)
        facts = self._post("/healthz", None, method="GET")
        model = facts.get("model")
        if not model:
            raise ChunkFailed(f"fleet not ready: {facts}")
        self._dim = int(model["dim"])
        self._iteration = int(model["iteration"])
        self._tokens = self._fetch_tokens(int(model["vocab_size"]))
        self._index = {t: i for i, t in enumerate(self._tokens)}

    def _post(self, path: str, body, method: str = "POST") -> dict:
        resp = self.client.request(
            path, body=body, method=method,
            timeout_s=_BATCH_TIMEOUT_S, headers=dict(self._HEADERS),
        )
        if not resp.ok or resp.doc is None:
            raise ChunkFailed(
                f"{method} {path} -> {resp.status} "
                f"({resp.error_class})"
            )
        return resp.doc

    def _fetch_tokens(self, total: int) -> List[str]:
        tokens: List[str] = []
        while len(tokens) < total:
            doc = self._post(
                f"/v1/genes?offset={len(tokens)}&limit=1000", None,
                method="GET",
            )
            got = doc.get("genes", [])
            if not got:
                raise ChunkFailed(
                    f"vocab fetch stalled at {len(tokens)}/{total}"
                )
            tokens.extend(got)
        return tokens

    @property
    def tokens(self) -> Sequence[str]:
        return self._tokens

    @property
    def dim(self) -> int:
        return self._dim

    @property
    def iteration(self) -> int:
        return self._iteration

    def pressure(self) -> float:
        try:
            doc = self._post("/healthz", None, method="GET")
        except ChunkFailed:
            return 1.0  # unreachable fleet = maximal pressure: yield
        return float(doc.get("queue_depth", 0)) / max(
            1, int(doc.get("max_queue", 1))
        )

    def knn_rows(self, start: int, n: int, k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
        genes = self._tokens[start:start + n]
        ids = np.empty((n, k), dtype=np.int32)
        scores = np.empty((n, k), dtype=np.float32)
        done = 0
        while done < n:
            sub = genes[done:done + self._sub]
            doc = self._post("/v1/similar", {"genes": sub, "k": k})
            results = doc.get("results", [])
            if len(results) != len(sub):
                raise ChunkFailed(
                    f"{len(results)} results for {len(sub)} queries"
                )
            for i, r in enumerate(results):
                hits = r.get("neighbors", [])[:k]
                if len(hits) < k:
                    raise ChunkFailed(
                        f"gene {sub[i]!r}: {len(hits)} neighbors < "
                        f"k={k}"
                    )
                for j, h in enumerate(hits):
                    row = self._index.get(h["gene"])
                    if row is None:
                        raise ChunkFailed(
                            f"neighbor {h['gene']!r} not in fetched "
                            "vocab (swap mid-job?)"
                        )
                    ids[done + i, j] = row
                    scores[done + i, j] = h["score"]
            done += len(sub)
        return ids, scores

    def pair_scores(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        out: List[float] = []
        done = 0
        pairs = [list(p) for p in pairs]
        while done < len(pairs):
            sub = pairs[done:done + self._sub]
            doc = self._post("/v1/interaction", {"pairs": sub})
            recs = doc.get("scores", [])
            if len(recs) != len(sub):
                raise ChunkFailed("interaction result count mismatch")
            out.extend(round(float(r["score"]), 6) for r in recs)
            done += len(sub)
        return out

    def vector_rows(self, start: int, n: int) -> List[List[float]]:
        genes = self._tokens[start:start + n]
        out: List[List[float]] = []
        done = 0
        while done < n:
            sub = genes[done:done + self._sub]
            doc = self._post("/v1/embedding", {"genes": sub})
            embs = doc.get("embeddings", [])
            if len(embs) != len(sub):
                raise ChunkFailed("embedding result count mismatch")
            out.extend([float(v) for v in e["vector"]] for e in embs)
            done += len(sub)
        return out


class ShardGroupBackend:
    """The sharded fleet's scatter plane: chunk queries fan out through
    :class:`serve.shardgroup.ShardGroup` in sub-requests tagged
    ``X-Tenant: batch`` (``shardgroup.scatter_headers``), so every
    replica's FairQueue drains them at the batch weight.  Degraded
    answers (an owner group mid-failover) are retryable, not
    recordable — the artifact only ever holds full-rank answers.

    ``sub_queries`` is deliberately SMALLER than the front-door cap: a
    scatter leg is one uninterruptible unit of replica work, and the
    interactive p99 under batch load is bounded by that unit's service
    time — tenancy weighting orders queued requests but cannot preempt
    one in flight.  ``pressure_fn`` (cli.fleet wires the aggregator's
    normalized replica queue depth) feeds the Pacer's yield guard."""

    _HEADERS = {"X-Tenant": BATCH_TENANT}

    def __init__(self, group, pressure_fn=None, sub_queries: int = 16):
        from gene2vec_tpu.serve.shardgroup import scatter_headers

        self.group = group
        self._scatter_headers = scatter_headers
        self._pressure_fn = pressure_fn
        self._sub = max(1, min(
            int(group.config.max_queries_per_request),
            int(sub_queries),
        ))

    @property
    def tokens(self) -> Sequence[str]:
        return self.group.routing.tokens

    @property
    def dim(self) -> int:
        return int(self.group.routing.dim)

    @property
    def iteration(self) -> int:
        return int(self.group.routing.iteration)

    def pressure(self) -> float:
        if self._pressure_fn is None:
            return 0.0
        try:
            return float(self._pressure_fn())
        except Exception:
            return 1.0  # a broken signal reads as pressure: yield

    def knn_rows(self, start: int, n: int, k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
        tokens = self.tokens
        index = self.group.routing.index
        genes = list(tokens[start:start + n])
        ids = np.empty((n, k), dtype=np.int32)
        scores = np.empty((n, k), dtype=np.float32)
        done = 0
        while done < n:
            sub = genes[done:done + self._sub]
            with self._scatter_headers(dict(self._HEADERS)):
                status, doc = self.group.similar(
                    {"genes": sub, "k": k}
                )
            if status != 200:
                raise ChunkFailed(
                    f"scatter answered {status}: {doc.get('error')}"
                )
            results = doc.get("results", [])
            if len(results) != len(sub):
                raise ChunkFailed(
                    f"scatter returned {len(results)} results for "
                    f"{len(sub)} queries"
                )
            for i, r in enumerate(results):
                hits = r.get("neighbors", [])[:k]
                if r.get("degraded") or len(hits) < k:
                    raise ChunkFailed(
                        f"gene {sub[i]!r}: degraded/short answer "
                        f"({len(hits)} neighbors; shard down?)"
                    )
                for j, h in enumerate(hits):
                    row = index.get(h["gene"])
                    if row is None:
                        raise ChunkFailed(
                            f"neighbor {h['gene']!r} not in routing "
                            "vocab"
                        )
                    ids[done + i, j] = row
                    scores[done + i, j] = h["score"]
            done += len(sub)
        return ids, scores

    def pair_scores(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        out: List[float] = []
        done = 0
        pairs = [list(p) for p in pairs]
        while done < len(pairs):
            sub = pairs[done:done + self._sub]
            with self._scatter_headers(dict(self._HEADERS)):
                status, doc = self.group.interaction({"pairs": sub})
            if status != 200:
                raise ChunkFailed(
                    f"interaction answered {status}: {doc.get('error')}"
                )
            recs = doc.get("scores", [])
            if len(recs) != len(sub):
                raise ChunkFailed("interaction result count mismatch")
            for rec in recs:
                if rec.get("score") is None:
                    raise ChunkFailed(
                        f"pair {rec.get('pair')!r} degraded (owner "
                        "shard down?)"
                    )
                out.append(round(float(rec["score"]), 6))
            done += len(sub)
        return out

    def vector_rows(self, start: int, n: int) -> List[List[float]]:
        tokens = self.tokens
        genes = list(tokens[start:start + n])
        out: List[List[float]] = []
        done = 0
        while done < n:
            sub = genes[done:done + self._sub]
            with self._scatter_headers(dict(self._HEADERS)):
                status, doc = self.group.embedding({"genes": sub})
            if status != 200:
                raise ChunkFailed(
                    f"embedding answered {status}: {doc.get('error')}"
                )
            embs = doc.get("embeddings", [])
            if len(embs) != len(sub):
                raise ChunkFailed("embedding result count mismatch")
            out.extend([float(v) for v in e["vector"]] for e in embs)
            done += len(sub)
        return out


# -- the job loops ------------------------------------------------------------


def _render_w2v_rows(tokens: Sequence[str],
                     vectors: Sequence[Sequence[float]]) -> bytes:
    # byte-identical to io/emb_io.py write_word2vec_format rows
    return "".join(
        str(t) + " " + " ".join(repr(float(v)) for v in row) + "\n"
        for t, row in zip(tokens, vectors)
    ).encode("utf-8")


def _render_pair_rows(pairs: Sequence[Sequence[str]],
                      scores: Sequence[float]) -> bytes:
    return "".join(
        f"{a}\t{b}\t{round(float(s), 6)!r}\n"
        for (a, b), s in zip(pairs, scores)
    ).encode("utf-8")


def run_job(
    spec,
    backend,
    art: ChunkedArtifact,
    metrics=None,
    should_stop: Optional[Callable[[], bool]] = None,
    pace: Optional[Pacer] = None,
    progress: Optional[Callable[[int, int], None]] = None,
) -> Dict:
    """Drive one job to its finalized artifact (resuming past already
    committed chunks), returning goodput facts.  Raises
    :class:`JobCancelled` when ``should_stop`` fires between chunks and
    :class:`ChunkFailed` when the backend stays broken past the retry
    budget — in both cases committed progress stays on disk for the
    next attempt."""
    t0 = time.monotonic()
    resumed_records = art.records_done
    pace = pace if pace is not None else Pacer()
    tokens = list(backend.tokens)
    iteration = backend.iteration
    chunk_rows = max(1, int(getattr(spec, "chunk_rows", 256)))
    kind = spec.type

    if kind == "knn_graph":
        plan = _plan_graph(spec, tokens, backend)
    elif kind == "pair_scores":
        plan = _plan_pairs(spec, backend)
    elif kind == "export":
        plan = _plan_export(spec, tokens, backend)
    else:
        raise ValueError(f"unknown job type {kind!r}")
    total_chunks, total_records, compute = plan

    if art.chunks_done == 0 and kind == "knn_graph":
        art.write_tokens(tokens)
    last_chunk_s = 0.0
    for ci in range(art.chunks_done, total_chunks):
        if should_stop is not None and should_stop():
            raise JobCancelled(
                f"cancelled at chunk {ci}/{total_chunks}"
            )
        pace.wait(last_chunk_s, should_stop)
        tc = time.monotonic()
        with ambient_span(
            "batch_chunk", job=getattr(spec, "job_id", None),
            type=kind, chunk=ci,
        ) as span:
            data, records = _retrying(lambda: compute(ci, chunk_rows))
            art.append_chunk(data, records)
            span["records"] = records
        last_chunk_s = time.monotonic() - tc
        if metrics is not None:
            metrics.counter("batch_chunks_committed_total").inc()
            metrics.counter("batch_records_total").inc(records)
            if records and last_chunk_s > 0:
                # per-chunk goodput: the mixed-workload bench's batch
                # headline and the ledger's batch_graph_rows_per_sec
                metrics.gauge("batch_chunk_rows_per_sec").set(
                    records / last_chunk_s
                )
        if progress is not None:
            progress(art.records_done, total_records)

    meta = {
        "type": kind,
        "k": int(getattr(spec, "k", 0) or 0),
        "rows": total_records,
        "dim": int(backend.dim),
        "iteration": int(iteration),
        "chunk_rows": chunk_rows,
        "tokens_crc32": zlib.crc32(
            "\n".join(tokens).encode("utf-8")
        ) & 0xFFFFFFFF,
    }
    if kind == "export":
        meta["format"] = "word2vec"
    path = art.finalize(meta)
    wall = max(time.monotonic() - t0, 1e-9)
    new_records = art.records_done - resumed_records
    return {
        "artifact": path,
        "records": art.records_done,
        "chunks": art.chunks_done,
        "data_bytes": art.data_bytes,
        "resumed_records": resumed_records,
        "wall_s": round(wall, 3),
        "rows_per_sec": round(new_records / wall, 3),
        "yielded_s": round(pace.yielded_s, 3),
    }


def _plan_graph(spec, tokens, backend):
    v = len(tokens)
    k = int(spec.k)
    if v <= k:
        raise ValueError(f"vocab {v} too small for k={k}")

    def compute(ci: int, chunk_rows: int):
        start = ci * chunk_rows
        n = min(chunk_rows, v - start)
        ids, scores = backend.knn_rows(start, n, k)
        return pack_graph_rows(ids, scores), n

    chunk_rows = max(1, int(spec.chunk_rows))
    return (-(-v // chunk_rows), v, compute)


def _plan_pairs(spec, backend):
    pairs = [list(p) for p in (spec.pairs or [])]
    if not pairs:
        raise ValueError("pair_scores job needs a non-empty 'pairs' list")

    def compute(ci: int, chunk_rows: int):
        sub = pairs[ci * chunk_rows:(ci + 1) * chunk_rows]
        scores = backend.pair_scores([tuple(p) for p in sub])
        return _render_pair_rows(sub, scores), len(sub)

    chunk_rows = max(1, int(spec.chunk_rows))
    return (-(-len(pairs) // chunk_rows), len(pairs), compute)


def _plan_export(spec, tokens, backend):
    v = len(tokens)
    dim = backend.dim

    def compute(ci: int, chunk_rows: int):
        if ci == 0:
            # the word2vec "<count> <dim>" header is its own chunk so
            # row chunks stay aligned to record counts
            return f"{v} {dim}\n".encode("utf-8"), 0
        start = (ci - 1) * chunk_rows
        n = min(chunk_rows, v - start)
        vectors = backend.vector_rows(start, n)
        return _render_w2v_rows(tokens[start:start + n], vectors), n

    chunk_rows = max(1, int(spec.chunk_rows))
    return (1 + -(-v // chunk_rows), v, compute)
