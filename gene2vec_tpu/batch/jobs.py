"""Job lifecycle: journal, background worker, and the ``/v1/jobs``
HTTP surface.

One :class:`JobManager` per front door (a single replica's
:class:`serve.server.ServeApp` or the fleet proxy).  Jobs live under
``<jobs_root>/<job_id>/``:

* ``JOB.json`` — the journal (spec + state + progress), every write
  atomic (resilience/snapshot.py), so the manager can be SIGKILLed at
  any instruction and rebuild its queue from disk;
* ``DATA.bin`` / ``CURSOR.json`` / ``ARTIFACT.json`` — the chunk store
  (batch/artifact.py commit protocol).

Exactly ONE worker thread drains the queue FIFO: the batch plane is
background priority by definition, and a single in-flight job bounds
its interference with the interactive SLO on top of the FairQueue
weight and the pacing guard.  On :meth:`start`, journal states
``pending``/``running`` re-enqueue — a ``running`` job whose process
died resumes from its artifact cursor and still converges to the
bit-identical final artifact.

:func:`dispatch_jobs` maps the ``/v1/jobs`` routes onto a manager and
is shared verbatim by the single-replica server and the fleet proxy.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from gene2vec_tpu.batch.artifact import ChunkedArtifact
from gene2vec_tpu.batch.runner import (
    ChunkFailed,
    JobCancelled,
    Pacer,
    run_job,
)
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.resilience import snapshot as snap

JOB_SCHEMA = "gene2vec-tpu/batch-job/v1"
JOB_NAME = "JOB.json"
JOB_TYPES = ("knn_graph", "pair_scores", "export")
JOB_STATES = ("pending", "running", "done", "failed", "cancelled")

_JOB_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

#: artifact bytes returned per /v1/jobs/<id>/artifact page (base64 in
#: the JSON body); clients page with ?offset= until empty
_ARTIFACT_PAGE = 1 << 20

#: submitted pair lists are part of the journal — bound them so one
#: request cannot write an unbounded JOB.json
_MAX_PAIRS = 200_000


@dataclasses.dataclass
class JobSpec:
    """One job's immutable parameters (journaled verbatim, so a resume
    after SIGKILL replays exactly the same plan)."""

    type: str
    k: int = 10
    chunk_rows: int = 256
    pairs: Optional[List[List[str]]] = None
    job_id: Optional[str] = None

    @classmethod
    def from_body(cls, body: dict) -> "JobSpec":
        kind = body.get("type")
        if kind not in JOB_TYPES:
            raise ValueError(
                f"'type' must be one of {list(JOB_TYPES)}, got {kind!r}"
            )
        k = body.get("k", 10)
        if not isinstance(k, int) or not 1 <= k <= 256:
            raise ValueError("'k' must be an int in [1, 256]")
        chunk_rows = body.get("chunk_rows", 256)
        if not isinstance(chunk_rows, int) or not 1 <= chunk_rows <= 8192:
            raise ValueError("'chunk_rows' must be an int in [1, 8192]")
        pairs = body.get("pairs")
        if kind == "pair_scores":
            if (
                not isinstance(pairs, list) or not pairs
                or len(pairs) > _MAX_PAIRS
                or not all(
                    isinstance(p, list) and len(p) == 2
                    and all(isinstance(g, str) for g in p)
                    for p in pairs
                )
            ):
                raise ValueError(
                    "'pairs' must be a non-empty list of [gene, gene] "
                    f"(at most {_MAX_PAIRS})"
                )
        else:
            pairs = None
        job_id = body.get("job_id")
        if job_id is not None and not _JOB_ID_RE.match(str(job_id)):
            raise ValueError(
                "'job_id' must match [A-Za-z0-9][A-Za-z0-9._-]{0,63}"
            )
        return cls(
            type=kind, k=k, chunk_rows=chunk_rows, pairs=pairs,
            job_id=job_id,
        )

    def to_doc(self) -> dict:
        return {
            "type": self.type,
            "k": self.k,
            "chunk_rows": self.chunk_rows,
            "pairs": self.pairs,
            "job_id": self.job_id,
        }


class JobManager:
    """The jobs root + the one background worker.

    ``backend_factory`` builds the query backend lazily per job run
    (the served model may have swapped between jobs; each RUN pins the
    iteration it started against)."""

    def __init__(
        self,
        root: str,
        backend_factory: Callable,
        metrics=None,
        pacer_factory: Optional[Callable[..., Pacer]] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.backend_factory = backend_factory
        self.metrics = metrics
        self.pacer_factory = pacer_factory
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queue: List[str] = []  # graftcheck: shared=guarded by _lock (the _wake condition's lock); worker and route threads only touch it under `with self._wake`
        self._cancelled: set = set()  # graftcheck: shared=guarded by _lock, same discipline as _queue
        self._seq = 0  # graftcheck: shared=guarded by _lock (submit-side id mint)
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    # -- journal ----------------------------------------------------------

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.root, job_id)

    def _journal_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), JOB_NAME)

    def _read_journal(self, job_id: str) -> Optional[dict]:
        try:
            with open(self._journal_path(job_id), "r",
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def _write_journal(self, job_id: str, doc: dict) -> None:
        doc = dict(doc)
        doc["schema"] = JOB_SCHEMA
        doc["updated_unix"] = self._clock()
        snap.atomic_write_json(self._journal_path(job_id), doc)

    def _update(self, job_id: str, **fields) -> dict:
        doc = self._read_journal(job_id) or {}
        doc.update(fields)
        self._write_journal(job_id, doc)
        return doc

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "JobManager":
        """Recover the on-disk queue, then start the worker.  Jobs the
        dead process left ``running`` go FIRST (their artifact cursor
        already holds committed chunks), then ``pending`` in submit
        order."""
        running: List[Tuple[float, str]] = []
        pending: List[Tuple[float, str]] = []
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            entries = []
        for name in entries:
            doc = self._read_journal(name)
            if doc is None:
                continue
            state = doc.get("state")
            created = float(doc.get("created_unix", 0))
            if state == "running":
                running.append((created, name))
            elif state == "pending":
                pending.append((created, name))
        with self._wake:
            self._queue = [
                j for _, j in sorted(running) + sorted(pending)
            ]
        self._stopping.clear()
        self._thread = threading.Thread(
            target=self._work, name="batch-jobs", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- the /v1/jobs verbs ----------------------------------------------

    def submit(self, spec: JobSpec) -> dict:
        """Journal + enqueue.  Resubmitting an existing job_id is
        idempotent: done jobs return their status, dead ones
        re-enqueue (the journal survives, progress resumes)."""
        with self._wake:
            if spec.job_id is None:
                self._seq += 1
                spec = dataclasses.replace(
                    spec,
                    job_id=f"job-{int(self._clock() * 1000)}-{self._seq}",
                )
            job_id = spec.job_id
            existing = self._read_journal(job_id)
            if existing is not None:
                state = existing.get("state")
                if state in ("pending", "running") or (
                    state == "done"
                ):
                    return self.status(job_id)[1]
                # failed/cancelled: re-enqueue the journaled spec (NOT
                # the resubmitted one — the artifact cursor belongs to
                # the original plan)
                self._update(job_id, state="pending", error=None)
                if job_id not in self._queue:
                    self._queue.append(job_id)
                self._cancelled.discard(job_id)
                self._wake.notify_all()
                return self.status(job_id)[1]
            os.makedirs(self.job_dir(job_id), exist_ok=True)
            self._write_journal(job_id, {
                "spec": spec.to_doc(),
                "state": "pending",
                "created_unix": self._clock(),
                "records_done": 0,
                "records_total": None,
                "error": None,
            })
            self._queue.append(job_id)
            self._wake.notify_all()
        if self.metrics is not None:
            self.metrics.counter("batch_jobs_submitted_total").inc()
        return self.status(job_id)[1]

    def status(self, job_id: str) -> Tuple[int, dict]:
        doc = self._read_journal(job_id)
        if doc is None:
            return 404, {"error": f"no job {job_id!r}"}
        spec = doc.get("spec", {})
        out = {
            "job_id": job_id,
            "type": spec.get("type"),
            "state": doc.get("state"),
            "created_unix": doc.get("created_unix"),
            "updated_unix": doc.get("updated_unix"),
            "records_done": doc.get("records_done"),
            "records_total": doc.get("records_total"),
            "iteration": doc.get("iteration"),
            "error": doc.get("error"),
        }
        if doc.get("result"):
            out["result"] = doc["result"]
        return 200, out

    def list_jobs(self) -> dict:
        jobs = []
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            entries = []
        for name in entries:
            status, doc = self.status(name)
            if status == 200:
                jobs.append(doc)
        jobs.sort(key=lambda d: d.get("created_unix") or 0)
        return {"jobs": jobs}

    def cancel(self, job_id: str) -> Tuple[int, dict]:
        with self._wake:
            doc = self._read_journal(job_id)
            if doc is None:
                return 404, {"error": f"no job {job_id!r}"}
            state = doc.get("state")
            if state in ("done", "failed", "cancelled"):
                return 409, {
                    "error": f"job {job_id} already {state}",
                    "state": state,
                }
            self._cancelled.add(job_id)
            if job_id in self._queue:
                # not yet running: settle it right here
                self._queue.remove(job_id)
                self._update(job_id, state="cancelled")
                self._cancelled.discard(job_id)
        return 200, self.status(job_id)[1]

    def artifact(self, job_id: str, offset: int = 0,
                 limit: int = _ARTIFACT_PAGE,
                 part: str = "data") -> Tuple[int, dict]:
        """One page of the finalized artifact, base64 in JSON (the
        front doors speak JSON; clients page by ``offset``, reassemble,
        and verify against ``data_crc32``).  ``part`` selects the data
        bytes (default) or the tokens sidecar, so a remote client can
        rebuild a complete, :func:`~gene2vec_tpu.batch.artifact
        .load_graph`-loadable artifact dir."""
        doc = self._read_journal(job_id)
        if doc is None:
            return 404, {"error": f"no job {job_id!r}"}
        if doc.get("state") != "done":
            return 409, {
                "error": f"job {job_id} is {doc.get('state')}, not done",
                "state": doc.get("state"),
            }
        art = ChunkedArtifact(self.job_dir(job_id))
        manifest = art.manifest()
        if manifest is None:
            return 500, {"error": "done job has no artifact manifest"}
        if part == "data":
            path = art.data_path
        elif part == "tokens":
            path = os.path.join(self.job_dir(job_id), "TOKENS.txt")
            if not os.path.exists(path):
                return 404, {
                    "error": f"job {job_id} has no tokens sidecar "
                    f"({doc.get('spec', {}).get('type')} job)"
                }
        else:
            return 400, {"error": "part must be 'data' or 'tokens'"}
        offset = max(0, int(offset))
        limit = max(1, min(int(limit), _ARTIFACT_PAGE))
        total = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(offset)
            blob = f.read(limit)
        return 200, {
            "job_id": job_id,
            "part": part,
            "offset": offset,
            "total_bytes": total,
            "data_crc32": manifest["data_crc32"],
            "chunks": manifest["chunks"],
            "records": manifest["records"],
            "meta": manifest.get("meta", {}),
            "data_b64": base64.b64encode(blob).decode("ascii"),
            "eof": offset + len(blob) >= total,
        }

    # -- the worker -------------------------------------------------------

    def _next_job(self) -> Optional[str]:
        with self._wake:
            while not self._queue and not self._stopping.is_set():
                self._wake.wait(timeout=0.5)
            if self._stopping.is_set():
                return None
            return self._queue.pop(0)

    def _is_cancelled(self, job_id: str) -> bool:
        with self._lock:
            return job_id in self._cancelled

    def _work(self) -> None:
        while not self._stopping.is_set():
            job_id = self._next_job()
            if job_id is None:
                return
            try:
                self._run_one(job_id)
            except Exception as e:  # a job bug must not kill the lane
                self._update(
                    job_id, state="failed",
                    error=f"worker crash: {e!r}",
                )
                self._count_done("failed")

    def _count_done(self, state: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "batch_jobs_completed_total", labels={"state": state}
            ).inc()
            self.metrics.gauge("batch_job_running").set(0)

    def _run_one(self, job_id: str) -> None:
        doc = self._read_journal(job_id)
        if doc is None:
            return
        spec = JobSpec(**(doc.get("spec") or {}))
        backend = self.backend_factory()
        # pin the iteration: a resumed job must extend bytes computed
        # against the SAME model or the artifact would silently mix
        # iterations (the loop plane's mixed-merge lesson)
        expect = doc.get("iteration")
        if expect is not None and int(backend.iteration) != int(expect):
            self._update(
                job_id, state="failed",
                error=(
                    f"model swapped mid-job (journal iteration {expect}"
                    f", serving {backend.iteration}); resubmit under a "
                    "new job_id"
                ),
            )
            self._count_done("failed")
            return
        self._update(
            job_id, state="running", iteration=int(backend.iteration),
        )
        if self.metrics is not None:
            self.metrics.gauge("batch_job_running").set(1)

        def progress(done: int, total: int) -> None:
            self._update(
                job_id, records_done=done, records_total=total,
            )

        art = ChunkedArtifact(self.job_dir(job_id))
        pace = (
            self.pacer_factory(backend)
            if self.pacer_factory is not None
            else Pacer(guard=backend.pressure)
        )
        t0 = time.monotonic()
        try:
            with ambient_span(
                "batch_job", job=job_id, type=spec.type,
            ) as span:
                result = run_job(
                    spec, backend, art,
                    metrics=self.metrics,
                    should_stop=lambda: (
                        self._is_cancelled(job_id)
                        or self._stopping.is_set()
                    ),
                    pace=pace,
                    progress=progress,
                )
                span["records"] = result["records"]
        except JobCancelled:
            if self._stopping.is_set():
                # shutdown, not cancellation: stay "running" so the
                # next start() resumes from the committed cursor
                return
            self._update(job_id, state="cancelled")
            with self._lock:
                self._cancelled.discard(job_id)
            self._count_done("cancelled")
            return
        except (ChunkFailed, ValueError, OSError) as e:
            self._update(job_id, state="failed", error=str(e))
            self._count_done("failed")
            return
        self._update(
            job_id, state="done",
            records_done=result["records"],
            records_total=result["records"],
            result={
                "rows_per_sec": result["rows_per_sec"],
                "wall_s": result["wall_s"],
                "yielded_s": result["yielded_s"],
                "chunks": result["chunks"],
                "data_bytes": result["data_bytes"],
                "resumed_records": result["resumed_records"],
            },
        )
        with self._lock:
            self._cancelled.discard(job_id)
        self._count_done("done")
        if self.metrics is not None:
            self.metrics.gauge("batch_job_rows_per_sec").set(
                result["rows_per_sec"]
            )
            self.metrics.histogram("batch_job_seconds").observe(
                time.monotonic() - t0
            )


# -- the shared /v1/jobs route table ------------------------------------------


def dispatch_jobs(
    manager: Optional[JobManager], method: str, route: str,
    query: Dict[str, List[str]], body: Optional[dict],
) -> Tuple[int, dict]:
    """Map one ``/v1/jobs`` request onto a manager — shared by the
    single-replica server and the fleet front door so both speak the
    identical lifecycle contract (docs/BATCH.md#job-api)."""
    if manager is None:
        return 404, {
            "error": "batch jobs disabled (start with --jobs-dir)"
        }
    if route == "/v1/jobs":
        if method == "POST":
            try:
                spec = JobSpec.from_body(body or {})
            except ValueError as e:
                return 400, {"error": str(e)}
            return 200, manager.submit(spec)
        if method == "GET":
            return 200, manager.list_jobs()
        return 404, {"error": f"no route {method} {route}"}
    parts = route.split("/")
    # ["", "v1", "jobs", <id>] or ["", "v1", "jobs", <id>, <verb>]
    if len(parts) < 4 or not _JOB_ID_RE.match(parts[3]):
        return 404, {"error": f"no route {method} {route}"}
    job_id = parts[3]
    verb = parts[4] if len(parts) == 5 else None
    if verb is None and method == "GET":
        return manager.status(job_id)
    if verb == "cancel" and method == "POST":
        return manager.cancel(job_id)
    if verb == "artifact" and method == "GET":
        try:
            offset = int(query.get("offset", ["0"])[0])
            limit = int(query.get("limit", [str(_ARTIFACT_PAGE)])[0])
        except ValueError:
            return 400, {"error": "offset/limit must be integers"}
        if offset < 0 or limit < 1:
            return 400, {"error": "offset must be >= 0, limit >= 1"}
        part = query.get("part", ["data"])[0]
        return manager.artifact(job_id, offset, limit, part=part)
    return 404, {"error": f"no route {method} {route}"}
