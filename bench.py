"""Headline benchmark: SGNS training throughput (gene-pairs/sec).

Prints exactly ONE JSON line on stdout:
    {"metric": "sgns_pairs_per_sec", "value": N, "unit": "pairs/s",
     "vs_baseline": N}

``vs_baseline`` is measured, not assumed: the native C++ Hogwild SGNS
kernel (native/sgns_hogwild.cpp — the same lock-free multithreaded design
as the reference's gensim-Cython engine, ``src/gene2vec.py:59``, on all
available host cores) is timed on a slice of the same workload, and the
TPU rate is divided by its rate.  If the native library is unavailable,
the fallback is the XLA-CPU path in a subprocess.  All progress/log output
goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def synth_corpus(vocab_size: int, num_pairs: int, seed: int = 0):
    """Zipf-ish pair corpus at human-gene scale (reference: ~24k genes)."""
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(seed)
    # Zipf ranks give gensim-like skewed unigram counts.
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    pairs = rng.choice(vocab_size, size=(num_pairs, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size).astype(np.int64)
    vocab = Vocab([f"G{i}" for i in range(vocab_size)], counts)
    return PairCorpus(vocab, pairs)


def measure_pairs_per_sec(
    dim: int, vocab_size: int, num_pairs: int, batch_pairs: int, epochs: int = 4
) -> float:
    """Steady-state epoch throughput (first epoch = compile, excluded)."""
    import jax

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = synth_corpus(vocab_size, num_pairs)
    config = SGNSConfig(dim=dim, batch_pairs=batch_pairs, num_iters=epochs)
    trainer = SGNSTrainer(corpus, config)
    params = trainer.init()
    key = jax.random.PRNGKey(0)

    params, loss = trainer.train_epoch(params, key)  # compile + warmup
    float(loss)
    pairs_per_epoch = trainer.num_batches * trainer.config.batch_pairs
    t0 = time.perf_counter()
    for e in range(1, epochs):
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, e))
    float(loss)  # block
    dt = time.perf_counter() - t0
    rate = pairs_per_epoch * (epochs - 1) / dt
    log(
        f"platform={jax.devices()[0].platform} dim={dim} V={vocab_size} "
        f"N={num_pairs} batch={batch_pairs}: {rate:,.0f} pairs/s "
        f"({dt:.2f}s / {epochs - 1} epochs), final loss {float(loss):.4f}"
    )
    return rate


def hogwild_baseline(dim: int, vocab_size: int, num_pairs: int) -> float:
    """Measure the native C++ Hogwild kernel on this host's cores."""
    import os as _os

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.native_backend import HogwildSGNSTrainer, available

    if not available():
        raise RuntimeError("native Hogwild library unavailable")
    corpus = synth_corpus(vocab_size, num_pairs)
    trainer = HogwildSGNSTrainer(corpus, SGNSConfig(dim=dim))
    params = trainer.init()
    params, _ = trainer.train_epoch(params, seed=0)  # warm caches
    t0 = time.perf_counter()
    params, loss = trainer.train_epoch(params, seed=1)
    dt = time.perf_counter() - t0
    rate = num_pairs / dt
    log(
        f"hogwild x{trainer.n_threads} (of {_os.cpu_count()} cores) dim={dim} "
        f"V={vocab_size} N={num_pairs}: {rate:,.0f} pairs/s "
        f"({dt:.2f}s), loss {loss:.4f}"
    )
    return rate


def cpu_baseline(dim: int, vocab_size: int, batch_pairs: int, num_pairs: int) -> float:
    """Measure the CPU rate in a subprocess (fresh backend, all host cores)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_CPU_CHILD="1")
    env.pop("XLA_FLAGS", None)  # single CPU "device", all cores via Eigen
    out = subprocess.run(
        [
            sys.executable,
            os.path.abspath(__file__),
            f"--dim={dim}",
            f"--vocab={vocab_size}",
            f"--pairs={num_pairs}",
            f"--batch={batch_pairs}",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        raise RuntimeError(f"CPU baseline subprocess failed:\n{out.stdout}")
    return float(json.loads(out.stdout.strip().splitlines()[-1])["value"])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=200)
    ap.add_argument("--vocab", type=int, default=24447)  # reference gene count scale
    ap.add_argument("--pairs", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--cpu-pairs", type=int, default=200_000)
    args = ap.parse_args()

    if os.environ.get("BENCH_CPU_CHILD"):
        # Child mode: measure on this process's (CPU) backend, emit one line.
        import jax

        jax.config.update("jax_platforms", "cpu")
        rate = measure_pairs_per_sec(
            args.dim, args.vocab, args.pairs, args.batch, epochs=2
        )
        print(json.dumps({"metric": "cpu", "value": rate, "unit": "pairs/s"}))
        return

    tpu_rate = measure_pairs_per_sec(args.dim, args.vocab, args.pairs, args.batch)
    try:
        cpu_rate = hogwild_baseline(args.dim, args.vocab, args.cpu_pairs)
        vs = tpu_rate / cpu_rate
    except Exception as e:
        log(f"hogwild baseline failed ({e}); falling back to XLA-CPU")
        try:
            cpu_rate = cpu_baseline(args.dim, args.vocab, args.batch, args.cpu_pairs)
            vs = tpu_rate / cpu_rate
        except Exception as e2:  # baseline is best-effort; headline still prints
            log(f"cpu baseline failed: {e2}")
            vs = float("nan")
    print(
        json.dumps(
            {
                "metric": "sgns_pairs_per_sec",
                "value": round(tpu_rate, 1),
                "unit": "pairs/s",
                "vs_baseline": round(vs, 2) if vs == vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
