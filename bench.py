"""Headline benchmark: SGNS training throughput (gene-pairs/sec), gated on
embedding quality.

Prints exactly ONE JSON line on stdout:
    {"metric": "sgns_pairs_per_sec", "value": N, "unit": "pairs/s",
     "vs_baseline": N, "vs_32thread_equiv": N, "baseline_1core": N,
     "quality": {...}, "secondary": {...}}

Quality gate (VERDICT round-2 item 3): before any throughput is reported,
the HEADLINE configuration must demonstrably learn — loss escapes its init
plateau, planted clusters separate without collapse, and (when the
reference predictionData is present) holdout link-prediction AUC reaches
the sequential-oracle ballpark.  A failing gate withholds the headline
(value 0.0, exit 1): round 2 posted 6.9M pairs/s from a configuration
whose loss never moved, and that must be structurally impossible now.

Baseline honesty (round-2, VERDICT item 3): ``vs_baseline`` divides by the
*measured* native C++ Hogwild SGNS rate on this host's cores (the same
lock-free multithreaded design as the reference's gensim-Cython engine,
``src/gene2vec.py:59``).  The bench host exposes a single core, while the
reference runs 32 Hogwild threads, so we additionally report
``vs_32thread_equiv`` — the TPU rate against a LINEAR 32x extrapolation of
the measured per-core rate.  Linear scaling is an upper bound for Hogwild
(lock-free updates contend for cache lines), so ``vs_32thread_equiv`` is a
*conservative lower bound* on the true speedup.  When >=2 cores exist the
thread-scaling curve is measured and reported on stderr.

Timing discipline (see docs/PERF_NOTES.md): the first two epochs are
warmup — epoch 1 compiles, epoch 2 pays a one-time donated-buffer
relayout — and only steady-state epochs are timed, with a scalar transfer
(float(loss)) forcing completion, since block_until_ready does not block
on the axon tunnel backend.

Secondary metrics (VERDICT item 7): CBOW/HS rate (BASELINE config 4),
dim=512 vocab-sharded rate (config 5, 1-device mesh on the bench chip;
the 8-way sharding itself is validated by dryrun_multichip), and the
GGIPNN training step rate.  They ride in the same JSON line under
"secondary" and are also written to BENCH_EXTRA.json.

All progress/log output goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def synth_corpus(vocab_size: int, num_pairs: int, seed: int = 0):
    """Zipf-ish pair corpus at human-gene scale (reference: ~24k genes)."""
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(seed)
    # Zipf ranks give gensim-like skewed unigram counts.
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    pairs = rng.choice(vocab_size, size=(num_pairs, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size).astype(np.int64)
    vocab = Vocab([f"G{i}" for i in range(vocab_size)], counts)
    return PairCorpus(vocab, pairs)


_LAST_RATES: list = []  # per-epoch rates of the most recent _steady_rate


def _bench_timeline():
    """Module-level phase timeline shared by every in-process
    ``_steady_rate`` call.  Disabled until main() enables it, so the
    dedicated-process probes (which import bench and call _steady_rate
    directly) pay nothing; main() flushes it into the bench run dir."""
    global _TIMELINE
    if _TIMELINE is None:
        from gene2vec_tpu.obs.timeline import PhaseTimeline

        _TIMELINE = PhaseTimeline(enabled=False)
    return _TIMELINE


_TIMELINE = None


def _steady_rate(trainer, warmup: int = 2, timed: int = 3, timeline=None) -> float:
    """Steady-state epoch throughput: warmup epochs excluded, each timed
    epoch synced via a scalar transfer, MEDIAN of the timed epochs returned
    (round-2 advisor: best-of-N is the most flattering defensible statistic;
    the median is the conventional honest headline — all repetitions are
    logged to stderr).  The raw repetitions land in ``_LAST_RATES`` so the
    headline JSON can carry the measured band (min..max), not just the
    median — the recorded ratio is a band because both numerator and the
    host-CPU denominator swing run to run (round-4 VERDICT item on number
    drift)."""
    import jax

    tl = timeline if timeline is not None else _bench_timeline()
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    for w in range(warmup):
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, w))
        float(loss)
    pairs_per_epoch = trainer.num_batches * trainer.config.batch_pairs
    rates = []
    for e in range(timed):
        t0 = time.perf_counter()
        with tl.phase("dispatch", step=e):
            params, loss = trainer.train_epoch(
                params, jax.random.fold_in(key, 100 + e)
            )
        with tl.phase("compute", step=e):
            float(loss)
        dt = time.perf_counter() - t0
        rates.append(pairs_per_epoch / dt)
    log(
        "  rates: "
        + ", ".join(f"{r:,.0f}" for r in rates)
        + f" pairs/s; final loss {float(loss):.4f}"
    )
    _LAST_RATES[:] = rates
    return float(np.median(rates))


def measure_pairs_per_sec(
    dim: int, vocab_size: int, num_pairs: int, batch_pairs: int,
    mesh_data: int = 0,
) -> tuple:
    """Headline rate; ``mesh_data > 0`` runs the SAME config data-parallel
    over the first N attached devices (sharded corpus + batch, replicated
    tables — XLA's scatter-into-replicated psum IS the gradient
    all-reduce, parallel/sharding.py).  Loss parity of the mesh path vs
    single-device is pinned by tests/test_parallel.py and the committed
    MESH_SANITY artifact (8-way CPU mesh); this flag makes the multi-chip
    headline one command when hardware is attached:
    ``python bench.py --mesh-data 8``.  Returns (rate, mesh_info)."""
    import jax

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = synth_corpus(vocab_size, num_pairs)
    config = SGNSConfig(dim=dim, batch_pairs=batch_pairs)
    sharding = None
    mesh_info = {
        "devices": 1,
        "platform": jax.devices()[0].platform,
        "mesh": None,
    }
    if mesh_data > 0:
        from gene2vec_tpu.config import MeshConfig
        from gene2vec_tpu.parallel.mesh import make_mesh
        from gene2vec_tpu.parallel.sharding import SGNSSharding

        devs = jax.devices()
        mesh = make_mesh(
            MeshConfig(data=mesh_data, model=1), devices=devs[:mesh_data]
        )
        sharding = SGNSSharding(mesh, vocab_sharded=False)
        mesh_info = {
            "devices": mesh_data,
            "platform": devs[0].platform,
            "mesh": {"data": mesh_data, "model": 1},
        }
    trainer = SGNSTrainer(corpus, config, sharding=sharding)
    rate = _steady_rate(trainer)
    mesh_info["rate_band"] = [
        round(min(_LAST_RATES), 1), round(max(_LAST_RATES), 1)
    ]
    log(
        f"platform={mesh_info['platform']} devices={mesh_info['devices']} "
        f"dim={dim} V={vocab_size} "
        f"N={num_pairs} batch={batch_pairs}: {rate:,.0f} pairs/s steady-state"
    )
    return rate, mesh_info


def headline_probe(
    dim: int, vocab_size: int, num_pairs: int, batch_pairs: int
):
    """The HEADLINE rate, measured in a DEDICATED subprocess before this
    process touches the TPU.  PERF_NOTES measurement discipline #3:
    a config measured after other stages share the chip reads up to ~35%
    below its fresh-process rate (the round-4/5 headline itself reads
    ~4-10% low after the quality-gate stages).  The subprocess runs the
    identical `_steady_rate` protocol; returns (median, [min, max]) or
    None, in which case main() falls back to the in-process measurement.
    """
    import subprocess

    probe = (
        "import json\n"
        "from bench import synth_corpus, _steady_rate, _LAST_RATES\n"
        "from gene2vec_tpu.config import SGNSConfig\n"
        "from gene2vec_tpu.sgns.train import SGNSTrainer\n"
        f"corpus = synth_corpus({vocab_size}, {num_pairs})\n"
        f"tr = SGNSTrainer(corpus, SGNSConfig(dim={dim}, "
        f"batch_pairs={batch_pairs}))\n"
        "r = _steady_rate(tr)\n"
        "print('HEADLINE', json.dumps([r, min(_LAST_RATES), "
        "max(_LAST_RATES)]))\n"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            timeout=900, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        vals = [
            json.loads(ln.split(None, 1)[1])
            for ln in res.stdout.splitlines()
            if ln.startswith("HEADLINE")
        ]
        if not vals:
            raise RuntimeError(res.stderr[-500:])
        med, lo, hi = vals[0]
        log(
            f"headline (dedicated process): {med:,.0f} pairs/s "
            f"[{lo:,.0f}..{hi:,.0f}]"
        )
        return round(med, 1), [round(lo, 1), round(hi, 1)]
    except Exception as e:
        log(f"headline probe failed ({e}); falling back to in-process")
        return None


def bf16_table_probe(vocab_size: int, num_pairs: int, batch_pairs: int):
    """Measured opt-in: bf16 table storage (+7% at real-scale quality
    parity; NOT the gated headline config — the f32 default is, since
    bf16 absorbs small-scale updates.  PERF_NOTES geometry II note).

    Runs in a SUBPROCESS and must be called BEFORE the parent touches
    the TPU: measured in-process after the headline stages — or even in
    a subprocess while the parent holds device buffers — the same
    config reads ~35% lower (6.2M alone vs ~4.0M sharing the chip,
    same minute; PERF_NOTES measurement discipline #3).  Returns the
    rate or None."""
    import subprocess

    probe = (
        "from bench import synth_corpus, _steady_rate\n"
        "from gene2vec_tpu.config import SGNSConfig\n"
        "from gene2vec_tpu.sgns.train import SGNSTrainer\n"
        f"corpus = synth_corpus({vocab_size}, {num_pairs})\n"
        "tr = SGNSTrainer(corpus, SGNSConfig(dim=200, "
        f"batch_pairs={batch_pairs}, table_dtype='bfloat16'))\n"
        "print('BF16_RATE', _steady_rate(tr))\n"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            timeout=900, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        rate = [
            float(ln.split()[1])
            for ln in res.stdout.splitlines()
            if ln.startswith("BF16_RATE")
        ]
        if not rate:
            raise RuntimeError(res.stderr[-500:])
        log(
            f"bf16 tables (opt-in, dedicated process): "
            f"{rate[0]:,.0f} pairs/s"
        )
        return round(rate[0], 1)
    except Exception as e:
        log(f"bf16-table probe failed: {e}")
        return None


def hogwild_baseline(dim: int, vocab_size: int, num_pairs: int):
    """Measured native C++ Hogwild rates: (best multi-thread rate on this
    host, measured 1-thread rate, thread->rate curve)."""
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.native_backend import HogwildSGNSTrainer, available

    if not available():
        raise RuntimeError("native Hogwild library unavailable")
    corpus = synth_corpus(vocab_size, num_pairs)
    ncores = os.cpu_count() or 1
    curve = {}
    threads_to_try = sorted({1, min(2, ncores), min(4, ncores), ncores})
    for nt in threads_to_try:
        trainer = HogwildSGNSTrainer(corpus, SGNSConfig(dim=dim), n_threads=nt)
        params = trainer.init()
        params, _ = trainer.train_epoch(params, seed=0)  # warm caches
        # this shared host's per-core rate swings ±30% run to run; the
        # headline ratio's denominator uses the median of 3 epochs so a
        # single slow/fast second doesn't decide the recorded number
        rates = []
        for rep in range(3):
            t0 = time.perf_counter()
            params, loss = trainer.train_epoch(params, seed=1 + rep)
            rates.append(num_pairs / (time.perf_counter() - t0))
        curve[nt] = float(np.median(rates))
        log(
            f"hogwild x{nt} (of {ncores} cores) dim={dim}: "
            f"{curve[nt]:,.0f} pairs/s (median of "
            f"{', '.join(f'{r:,.0f}' for r in rates)}), loss {loss:.4f}"
        )
    return max(curve.values()), curve[1], curve


def secondary_metrics(vocab_size: int, num_pairs: int, batch_pairs: int) -> dict:
    """CBOW/HS, dim=512 vocab-sharded, GGIPNN, and shared-mode SGNS rates."""
    import jax

    out = {}

    # round-2 default (shared-pool negatives) for cross-round comparability
    # against the round-3 stratified headline
    try:
        from gene2vec_tpu.config import SGNSConfig
        from gene2vec_tpu.sgns.train import SGNSTrainer

        corpus = synth_corpus(vocab_size, num_pairs)
        trainer = SGNSTrainer(
            corpus,
            SGNSConfig(
                dim=200, batch_pairs=batch_pairs, negative_mode="shared"
            ),
        )
        out["shared_mode_pairs_per_sec"] = round(_steady_rate(trainer), 1)
        log(f"shared mode: {out['shared_mode_pairs_per_sec']:,.0f} pairs/s")
    except Exception as e:
        log(f"shared-mode secondary failed: {e}")

    # BASELINE config 4: CBOW + hierarchical softmax.
    try:
        from gene2vec_tpu.config import SGNSConfig
        from gene2vec_tpu.sgns.cbow_hs import CBOWHSTrainer

        corpus = synth_corpus(vocab_size, num_pairs)
        cfg = SGNSConfig(
            dim=200, batch_pairs=batch_pairs, objective="cbow_hs"
        )
        trainer = CBOWHSTrainer(corpus, cfg)
        out["cbow_hs_pairs_per_sec"] = round(_steady_rate(trainer), 1)
        log(f"cbow/hs: {out['cbow_hs_pairs_per_sec']:,.0f} pairs/s")
    except Exception as e:
        log(f"cbow/hs secondary failed: {e}")

    # ... and its CPU anchor (round 5): the native Hogwild HS oracle on
    # this host's core(s), same 32-thread linear extrapolation discipline
    # as the SGNS headline denominator (an upper bound on Hogwild
    # scaling, hence a conservative ratio).
    try:
        from gene2vec_tpu.sgns.native_backend import (
            HogwildHSTrainer, available,
        )

        if not available():
            raise RuntimeError("native library unavailable")
        corpus = synth_corpus(vocab_size, 200_000)
        tr = HogwildHSTrainer(
            corpus, SGNSConfig(dim=200, objective="cbow_hs"), n_threads=1
        )
        params = tr.init()
        params, _ = tr.train_epoch(params)  # warm caches
        rates = []
        for rep in range(3):
            t0 = time.perf_counter()
            params, hs_loss = tr.train_epoch(params)
            rates.append(corpus.num_pairs / (time.perf_counter() - t0))
        hs_1core = float(np.median(rates))
        out["cbow_hs_cpu_1core_pairs_per_sec"] = round(hs_1core, 1)
        if "cbow_hs_pairs_per_sec" in out:
            out["cbow_hs_vs_32thread_equiv"] = round(
                out["cbow_hs_pairs_per_sec"] / (32.0 * hs_1core), 2
            )
            out["cbow_hs_vs_cpu_extrapolated"] = True
        log(
            f"cbow/hs native 1-core: {hs_1core:,.0f} pairs/s (loss "
            f"{hs_loss:.4f}); vs 32-thread-equiv = "
            f"{out.get('cbow_hs_vs_32thread_equiv')}"
        )
    except Exception as e:
        log(f"cbow/hs CPU anchor failed: {e}")

    # BASELINE config 5: dim=512 vocab-sharded row-parallel table. On the
    # single bench chip the mesh is (1, 1); the collective pattern itself
    # is exercised by dryrun_multichip on an 8-way CPU mesh.
    try:
        from jax.sharding import Mesh

        from gene2vec_tpu.config import SGNSConfig
        from gene2vec_tpu.parallel.sharding import SGNSSharding
        from gene2vec_tpu.sgns.train import SGNSTrainer

        corpus = synth_corpus(vocab_size, num_pairs)
        devs = np.asarray(jax.devices()[:1]).reshape(1, 1)
        mesh = Mesh(devs, ("data", "model"))
        sharding = SGNSSharding(mesh, vocab_sharded=True)
        cfg = SGNSConfig(dim=512, batch_pairs=batch_pairs, vocab_sharded=True)
        trainer = SGNSTrainer(corpus, cfg, sharding=sharding)
        out["dim512_sharded_pairs_per_sec"] = round(_steady_rate(trainer), 1)
        log(f"dim512 sharded: {out['dim512_sharded_pairs_per_sec']:,.0f} pairs/s")
    except Exception as e:
        log(f"dim512 secondary failed: {e}")

    # GGIPNN training step rate (pairs/sec through the Flax MLP), with a
    # host-CPU denominator (VERDICT r3 item 8: the TF1 reference can't run
    # here, so the same jax step on the host CPU gives the rate a ratio
    # like the SGNS headline has).
    try:
        out["ggipnn_pairs_per_sec"] = round(_ggipnn_rate(), 1)
        log(f"ggipnn: {out['ggipnn_pairs_per_sec']:,.0f} pairs/s")
        cpu = [d for d in jax.local_devices(backend="cpu")]
        if cpu:
            out["ggipnn_cpu_pairs_per_sec"] = round(
                _ggipnn_rate(n_pairs=65536, device=cpu[0]), 1
            )
            out["ggipnn_vs_cpu"] = round(
                out["ggipnn_pairs_per_sec"]
                / out["ggipnn_cpu_pairs_per_sec"], 2
            )
            log(
                f"ggipnn cpu: {out['ggipnn_cpu_pairs_per_sec']:,.0f} pairs/s"
                f" (tpu/cpu = {out['ggipnn_vs_cpu']})"
            )
    except Exception as e:
        log(f"ggipnn secondary failed: {e}")
    return out


def _ggipnn_rate(n_pairs: int = 262144, batch: int = 1024, device=None) -> float:
    """Synthetic GGIPNN training epoch rate at the reference's data scale
    (263,016 train pairs, ``wc -l predictionData/train_text.txt``).  The
    batch is 1024 rather than the reference's dispatch-bound 128 — this is
    a device-throughput metric; the reference-faithful cadence lives in
    ``run_classification``.  ``device`` pins the run (e.g. the host CPU
    backend for the baseline ratio); None uses the default device."""
    import contextlib

    import jax

    ctx = jax.default_device(device) if device is not None else (
        contextlib.nullcontext()
    )
    with ctx:
        return _ggipnn_rate_impl(n_pairs, batch)


def _ggipnn_rate_impl(n_pairs: int, batch: int) -> float:
    import jax

    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_data import PairTextVocab
    from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer

    rng = np.random.RandomState(0)
    vocab_size = 24447
    x = rng.randint(0, vocab_size, (n_pairs, 2)).astype(np.int32)
    labels = rng.randint(0, 2, n_pairs)
    y = np.eye(2, dtype=np.float32)[labels]
    vocab = PairTextVocab().fit(f"G{i} G{i}" for i in range(vocab_size))
    cfg = GGIPNNConfig(batch_size=batch, num_epochs=1, scan_fit=True)
    trainer = GGIPNNTrainer(cfg, vocab)
    params, opt_state = trainer.init_state()  # random table (SURVEY §2.2 #13)
    import jax.numpy as jnp

    xj = jnp.asarray(x)
    yj = jnp.asarray(y)
    num_batches = n_pairs // batch
    key = jax.random.PRNGKey(0)
    # epoch 1 compiles, epoch 2 pays donated-buffer relayout; time epoch 3
    for w in range(2):
        params, opt_state, loss, _ = trainer.fit_epoch(
            params, opt_state, xj, yj, jax.random.fold_in(key, w)
        )
        float(loss)
    t0 = time.perf_counter()
    params, opt_state, loss, _ = trainer.fit_epoch(
        params, opt_state, xj, yj, jax.random.fold_in(key, 9)
    )
    float(loss)
    dt = time.perf_counter() - t0
    return num_batches * batch / dt


def bench_stamp(doc: dict) -> dict:
    """Stamp provenance into a bench JSON: ``schema_version`` + the
    producing command + creation time, so the ledger
    (gene2vec_tpu/obs/ledger.py) can tell a freshly produced record
    from a legacy unstamped artifact and reproduce it.  Delegates to
    the ledger's canonical ``provenance_stamp`` — the quality-eval
    producers (scripts/run_intrinsic.py, scripts/run_real_auc.py,
    cli.evaluate) stamp through the same convention."""
    from gene2vec_tpu.obs.ledger import provenance_stamp

    return provenance_stamp(doc)


def timeline_overhead(
    dim: int, vocab: int, num_pairs: int, batch_pairs: int, rounds: int,
    epochs_per_window: int = 2,
) -> dict:
    """Timeline-on vs timeline-off SGNS throughput at the pinned
    BENCH_PERF recipe (budgets.json "perf", section
    ``timeline_overhead``).

    One trainer, warmed once; then ``rounds`` window pairs with
    ALTERNATING arm order (the BENCH_OBS lesson: this host's window
    rates swing several percent between identical windows, so each
    arm's estimate is the MEDIAN of its per-window rates).  The ON arm
    runs the exact per-epoch instrumentation the trainers use
    (``tl.phase("dispatch")`` + ``tl.phase("compute")``); the OFF arm
    runs the same wrappers disabled — precisely the
    ``SGNSConfig.timeline`` toggle's two states."""
    import jax

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.obs.timeline import PhaseTimeline
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = synth_corpus(vocab, num_pairs)
    trainer = SGNSTrainer(
        corpus, SGNSConfig(dim=dim, batch_pairs=batch_pairs)
    )
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    for w in range(2):  # epoch 1 compiles, epoch 2 pays the relayout
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, w))
        float(loss)
    pairs_per_epoch = trainer.num_batches * trainer.config.batch_pairs
    arms = {False: PhaseTimeline(enabled=False), True: PhaseTimeline()}
    rates: dict = {False: [], True: []}
    e = 0
    for r in range(rounds):
        order = (False, True) if r % 2 == 0 else (True, False)
        for arm in order:
            tl = arms[arm]
            t0 = time.perf_counter()
            for _ in range(epochs_per_window):
                with tl.phase("dispatch", step=e):
                    params, loss = trainer.train_epoch(
                        params, jax.random.fold_in(key, 100 + e)
                    )
                with tl.phase("compute", step=e):
                    float(loss)
                e += 1
            dt = time.perf_counter() - t0
            rates[arm].append(pairs_per_epoch * epochs_per_window / dt)
    off = float(np.median(rates[False]))
    on = float(np.median(rates[True]))
    doc = {
        "bench": "timeline_overhead",
        "recipe": {
            "dim": dim, "vocab": vocab, "num_pairs": num_pairs,
            "batch_pairs": batch_pairs, "rounds": rounds,
            "epochs_per_window": epochs_per_window,
        },
        "window_rates_off": [round(v, 1) for v in rates[False]],
        "window_rates_on": [round(v, 1) for v in rates[True]],
        "rate_timeline_off": round(off, 1),
        "rate_timeline_on": round(on, 1),
        "regression_frac": round((off - on) / off, 4) if off > 0 else None,
    }
    log(
        f"timeline overhead: off {off:,.0f} on {on:,.0f} pairs/s, "
        f"regression {doc['regression_frac']}"
    )
    return bench_stamp(doc)


def kernel_profile_bench(recipe: dict) -> dict:
    """Kernel cost-attribution bench (``--kernel-profile``): roofline
    records for every registered compute hot path at the pinned recipe
    (budgets.json ``kernels.profile``), stamped into
    ``BENCH_KERNELS_r*.json`` and gated by ``analysis/passes_kernels.py``.

    Two halves.  (1) ATTRIBUTION: each kernel is AOT lowered+compiled
    (static FLOPs / bytes accessed / peak memory from XLA's
    compiled-computation cost analysis, plus lowering/compile wall
    seconds) and then executed through its PRODUCTION entry point —
    donated-buffer epoch fns are timed by threading state through
    real epochs, never by replaying consumed args — deriving
    achieved-vs-peak utilization against the per-backend peak table
    (obs/profiler.py).  (2) OVERHEAD: the profiler's only steady-state
    cost is one ``kp.observe`` per epoch (attribution is warm-time;
    nothing runs per batch inside the scan), measured with the
    BENCH_OBS/BENCH_PERF methodology — one warmed trainer, alternating
    off/on window pairs, median per arm."""
    import jax
    import jax.numpy as jnp

    from gene2vec_tpu.config import GGIPNNConfig, SGNSConfig
    from gene2vec_tpu.models.ggipnn_data import PairTextVocab
    from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer
    from gene2vec_tpu.obs import profiler as prof
    from gene2vec_tpu.serve import ann as ann_mod
    from gene2vec_tpu.serve.engine import BucketedTopKEngine
    from gene2vec_tpu.sgns.cbow_hs import CBOWHSTrainer
    from gene2vec_tpu.sgns.train import SGNSTrainer

    dim = int(recipe.get("dim", 64))
    vocab = int(recipe.get("vocab", 2048))
    num_pairs = int(recipe.get("num_pairs", 65536))
    batch_pairs = int(recipe.get("batch_pairs", 2048))
    serve_rows = int(recipe.get("serve_rows", 2048))
    serve_dim = int(recipe.get("serve_dim", 64))
    serve_batch = int(recipe.get("serve_batch", 16))
    serve_k = int(recipe.get("serve_k", 16))
    serve_clusters = int(recipe.get("serve_clusters", 64))
    ggipnn_pairs = int(recipe.get("ggipnn_pairs", 8192))
    ggipnn_batch = int(recipe.get("ggipnn_batch", 512))
    rounds = int(recipe.get("rounds", 5))
    epochs_per_window = int(recipe.get("epochs_per_window", 2))

    p = prof.KernelProfiler()
    key = jax.random.PRNGKey(0)

    # --- sgns_train_step: attribute the epoch fn, then time REAL epochs
    # threading params (the epoch fn donates its buffers — replaying a
    # consumed params arg would crash, docs/PERF_NOTES.md)
    log("=== kernel profile: sgns_train_step ===")
    corpus = synth_corpus(vocab, num_pairs)
    trainer = SGNSTrainer(
        corpus, SGNSConfig(dim=dim, batch_pairs=batch_pairs)
    )
    params = trainer.init()
    p.attribute(
        "sgns_train_step", trainer._epoch_fn,
        (params, trainer.pairs, trainer.noise, jax.random.fold_in(key, 0)),
    )
    for w in range(2):  # epoch 1 compiles, epoch 2 pays the relayout
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, w))
        float(loss)
    for e in range(3):
        t0 = time.perf_counter()
        params, loss = trainer.train_epoch(
            params, jax.random.fold_in(key, 100 + e)
        )
        float(loss)
        p.observe("sgns_train_step", time.perf_counter() - t0)

    # --- cbow_hs_step: same discipline via the trainer's profile hook
    log("=== kernel profile: cbow_hs_step ===")
    ctrainer = CBOWHSTrainer(
        corpus, SGNSConfig(
            dim=dim, batch_pairs=batch_pairs, objective="cbow_hs"
        )
    )
    cparams = ctrainer.init()
    ctrainer.profile_kernel(p, params=cparams)
    for w in range(2):
        cparams, loss = ctrainer.train_epoch(
            cparams, jax.random.fold_in(key, w)
        )
        float(loss)
    for e in range(3):
        t0 = time.perf_counter()
        cparams, loss = ctrainer.train_epoch(
            cparams, jax.random.fold_in(key, 100 + e)
        )
        float(loss)
        p.observe("cbow_hs_step", time.perf_counter() - t0)

    # --- ggipnn_step: static cost is ONE train step (the trainer's
    # profile hook jits the non-donating step impl); dynamic epochs are
    # divided back to per-step via observe(calls=num_batches)
    log("=== kernel profile: ggipnn_step ===")
    rng = np.random.RandomState(0)
    gx = jnp.asarray(
        rng.randint(0, vocab, (ggipnn_pairs, 2)).astype(np.int32)
    )
    gy = jnp.asarray(
        np.eye(2, dtype=np.float32)[rng.randint(0, 2, ggipnn_pairs)]
    )
    gvocab = PairTextVocab().fit(f"G{i} G{i}" for i in range(vocab))
    gtrainer = GGIPNNTrainer(
        GGIPNNConfig(batch_size=ggipnn_batch, num_epochs=1, scan_fit=True),
        gvocab,
    )
    gparams, gopt = gtrainer.init_state()
    gtrainer.profile_kernel(
        p, gparams, gopt, gx[:ggipnn_batch], gy[:ggipnn_batch]
    )
    gnb = ggipnn_pairs // ggipnn_batch
    for w in range(2):
        gparams, gopt, loss, _ = gtrainer.fit_epoch(
            gparams, gopt, gx, gy, jax.random.fold_in(key, w)
        )
        float(loss)
    for e in range(3):
        t0 = time.perf_counter()
        gparams, gopt, loss, _ = gtrainer.fit_epoch(
            gparams, gopt, gx, gy, jax.random.fold_in(key, 100 + e)
        )
        float(loss)
        p.observe("ggipnn_step", time.perf_counter() - t0, calls=gnb)

    # --- serve top-k bucket per index mode + the raw int8 ANN scan
    log("=== kernel profile: serve engine buckets ===")
    table = _ann_clustered_table(serve_rows, serve_dim, serve_clusters, 0)
    unit = jnp.asarray(table)
    unit.block_until_ready()
    quant = ann_mod.build_index(table, "quant")
    ivf = ann_mod.build_index(table, "ivf", clusters=serve_clusters, seed=0)
    qs = table[:serve_batch]
    for mode, idx in (("exact", None), ("quant", quant), ("ivf", ivf)):
        eng = BucketedTopKEngine(max_batch=serve_batch, index=mode)
        recs = eng.profile_buckets(
            unit, k=serve_k, ann_index=idx, buckets=[serve_batch]
        )
        rec = next(iter(recs.values()))
        name = f"serve_topk_{mode}"
        p.register_costs(name, {
            f: rec.get(f) for f in (
                "flops", "bytes_accessed", "peak_memory_bytes",
                "lower_s", "compile_s",
            )
        })
        if mode == "exact":
            call = lambda: eng.top_k(unit, qs, serve_k)  # noqa: E731
        else:
            call = (  # noqa: E731
                lambda i=idx, e=eng: e.top_k_ann(i, unit, qs, serve_k)
            )
        call()  # warm this bucket (returns host arrays: synced)
        for _ in range(3):
            t0 = time.perf_counter()
            call()
            p.observe(name, time.perf_counter() - t0)
    scan = jax.jit(ann_mod._approx_scores)
    scan_args = (jnp.asarray(qs), quant.table_q, quant.scale)
    p.attribute("ann_int8_scan", scan, scan_args)
    p.measure("ann_int8_scan", scan, scan_args, iters=3, warmup=1)

    # --- overhead: profiler-on vs profiler-off SGNS windows, the
    # timeline_overhead methodology (alternating arm order, median per
    # arm); the ON arm's whole steady-state cost is one observe/epoch
    log("=== kernel profile: overhead windows ===")
    pairs_per_epoch = trainer.num_batches * trainer.config.batch_pairs
    kp_arm = prof.KernelProfiler()
    rates: dict = {False: [], True: []}
    e = 0
    for r in range(rounds):
        order = (False, True) if r % 2 == 0 else (True, False)
        for arm in order:
            t0 = time.perf_counter()
            for _ in range(epochs_per_window):
                te = time.perf_counter()
                params, loss = trainer.train_epoch(
                    params, jax.random.fold_in(key, 200 + e)
                )
                float(loss)
                if arm:
                    kp_arm.observe(
                        "sgns_train_step", time.perf_counter() - te
                    )
                e += 1
            dt = time.perf_counter() - t0
            rates[arm].append(pairs_per_epoch * epochs_per_window / dt)
    off = float(np.median(rates[False]))
    on = float(np.median(rates[True]))
    overhead = {
        "window_rates_off": [round(v, 1) for v in rates[False]],
        "window_rates_on": [round(v, 1) for v in rates[True]],
        "rate_profile_off": round(off, 1),
        "rate_profile_on": round(on, 1),
        "regression_frac": round((off - on) / off, 4) if off > 0 else None,
    }
    log(
        f"kernel-profile overhead: off {off:,.0f} on {on:,.0f} pairs/s, "
        f"regression {overhead['regression_frac']}"
    )

    kernels: dict = {}
    for rec in p.records():
        kernels[rec["name"]] = {
            "flops": rec["flops"],
            "bytes_accessed": rec["bytes_accessed"],
            "peak_memory_bytes": rec["peak_memory_bytes"],
            "lower_s": rec["lower_s"],
            "compile_s": rec["compile_s"],
            "calls": rec["calls"],
            # the pinned-shape headline: best observed per-call wall
            "wall_s": rec["best_wall_s"],
            "achieved_flops_per_sec": rec["achieved_flops_per_sec"],
            "achieved_bytes_per_sec": rec["achieved_bytes_per_sec"],
            "flops_util": rec["flops_util"],
            "bytes_util": rec["bytes_util"],
            "utilization": rec["utilization"],
            "bound": rec["bound"],
        }
        log(
            f"{rec['name']}: flops {rec['flops']}  bytes "
            f"{rec['bytes_accessed']}  best "
            f"{rec['best_wall_s']}s  util {rec['utilization']}"
        )
    doc = {
        "bench": "kernels",
        "recipe": {
            "dim": dim, "vocab": vocab, "num_pairs": num_pairs,
            "batch_pairs": batch_pairs, "serve_rows": serve_rows,
            "serve_dim": serve_dim, "serve_batch": serve_batch,
            "serve_k": serve_k, "serve_clusters": serve_clusters,
            "rounds": rounds, "epochs_per_window": epochs_per_window,
        },
        "backend": {**p.backend, **p.peaks},
        "kernels": kernels,
        "overhead": overhead,
    }
    return bench_stamp(doc)


def _ann_clustered_table(
    rows: int, dim: int, clusters: int, seed: int, spread: float = 0.35
) -> np.ndarray:
    """Synthetic L2-normalized table with mixture-of-centroid geometry —
    the shape real embedding tables have (trained embeddings cluster by
    function; QUALITY_NOTES' planted-set analysis is the small-scale
    version).  A uniform-random table is the adversarial IVF case and
    is covered by the recall harness's nprobe sweep in tests/."""
    from gene2vec_tpu.serve.registry import l2_normalize

    rng = np.random.RandomState(seed)
    cent = rng.randn(clusters, dim).astype(np.float32)
    assign = rng.randint(0, clusters, rows)
    out = np.empty((rows, dim), np.float32)
    step = 131072  # chunked: 1M x dim materializes once, not thrice
    for s in range(0, rows, step):
        block = cent[assign[s : s + step]]
        out[s : s + step] = (
            block + spread * rng.randn(*block.shape).astype(np.float32)
        )
    return l2_normalize(out)


def _ann_mode_latency(call, reps: int) -> dict:
    """p50/p99 of ``reps`` single-query calls (ms), first call excluded
    by the caller (compile)."""
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        call()
        samples.append((time.perf_counter() - t0) * 1000.0)
    arr = np.asarray(samples)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
    }


def ann_bench(
    rows: int, dim: int, k: int, queries: int, clusters: int,
    nprobe: int, rescore_mult: int, seed: int = 0,
    latency_reps: int = 50, real_rows: int = 24447, real_dim: int = 200,
) -> dict:
    """The approximate-retrieval scaling bench (``--ann``):
    exact vs int8-quantized vs IVF+int8 top-k on a synthetic clustered
    ``rows``-row table, recall@10 scored against the exact numpy
    oracle, p50/p99 per mode from single-query calls, analytic
    bytes-touched per query — plus the same recall check at the real
    24,447-vocab serving geometry.  Queries are drawn from table rows
    (the production ``/v1/similar`` workload: gene queries ARE table
    rows).  Stamped into ``BENCH_ANN_r*.json`` and gated by
    ``analysis/passes_ann.py`` against budgets.json ``ann.recall``."""
    import jax.numpy as jnp

    from gene2vec_tpu.serve import ann as ann_mod
    from gene2vec_tpu.serve.engine import BucketedTopKEngine

    rng = np.random.RandomState(seed)
    log(f"=== ANN bench: {rows:,} x {dim} synthetic clustered table ===")
    t0 = time.perf_counter()
    table = _ann_clustered_table(rows, dim, clusters, seed)
    unit = jnp.asarray(table)
    unit.block_until_ready()
    log(f"table built in {time.perf_counter() - t0:.1f}s "
        f"({table.nbytes / 1e6:.0f} MB f32)")

    q_idx = rng.choice(rows, queries, replace=False)
    qs = table[q_idx]
    t0 = time.perf_counter()
    oracle = ann_mod.exact_oracle(table, qs, k)
    log(f"numpy exact oracle over {queries} queries in "
        f"{time.perf_counter() - t0:.1f}s")

    engine = BucketedTopKEngine(
        max_batch=64, index="ivf", nprobe=nprobe,
        rescore_mult=rescore_mult,
    )
    t0 = time.perf_counter()
    quant = ann_mod.build_index(table, "quant")
    log(f"quant index built in {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    ivf = ann_mod.build_index(table, "ivf", clusters=clusters, seed=seed)
    log(f"ivf index built in {time.perf_counter() - t0:.1f}s "
        f"(C={ivf.n_clusters}, L={ivf.list_len})")

    rb = engine.r_bucket(engine.k_bucket(k, rows), rows)
    bytes_exact = ann_mod.bytes_per_query("exact", rows, dim)
    per_mode = {
        "exact": (
            lambda q, n: engine.top_k(unit, q, n),
            bytes_exact,
        ),
        "quant": (
            lambda q, n: engine.top_k_ann(quant, unit, q, n),
            ann_mod.bytes_per_query("quant", rows, dim, r=rb),
        ),
        "ivf": (
            lambda q, n: engine.top_k_ann(ivf, unit, q, n),
            ann_mod.bytes_per_query(
                "ivf", rows, dim, r=rb, clusters=ivf.n_clusters,
                list_len=ivf.list_len, nprobe=nprobe,
            ),
        ),
    }
    modes: dict = {}
    for mode, (call, bpq) in per_mode.items():
        found = np.empty((queries, k), np.int64)
        t0 = time.perf_counter()
        for s in range(0, queries, 64):
            _, idx = call(qs[s : s + 64], k)
            found[s : s + 64] = idx
        batch_s = time.perf_counter() - t0
        recall = ann_mod.recall_at_k(found, oracle)
        one = qs[:1]
        call(one, k)  # warm the B=1 bucket before timing
        lat = _ann_mode_latency(lambda: call(one, k), latency_reps)
        modes[mode] = {
            "recall_at_10": round(recall, 4),
            "bytes_per_query": bpq,
            "batched_queries_per_sec": round(queries / batch_s, 1),
            **lat,
        }
        log(f"{mode}: recall@{k} {recall:.4f}  p50 {lat['p50_ms']}ms  "
            f"p99 {lat['p99_ms']}ms  {bpq / 1e6:.2f} MB/query")
    modes["ivf"]["p99_speedup_vs_exact"] = round(
        modes["exact"]["p99_ms"] / max(modes["ivf"]["p99_ms"], 1e-9), 2
    )
    modes["ivf"]["bytes_reduction_vs_exact"] = round(
        bytes_exact / max(modes["ivf"]["bytes_per_query"], 1e-9), 1
    )
    modes["quant"]["bytes_reduction_vs_exact"] = round(
        bytes_exact / max(modes["quant"]["bytes_per_query"], 1e-9), 1
    )

    # the real serving geometry: same recall floor must hold at the
    # 24,447-vocab table the paper's checkpoints actually have
    log(f"=== real-geometry recall check: {real_rows:,} x {real_dim} ===")
    real_table = _ann_clustered_table(
        real_rows, real_dim, clusters=max(8, int(np.sqrt(real_rows))),
        seed=seed + 1,
    )
    real_unit = jnp.asarray(real_table)
    real_q = real_table[
        np.random.RandomState(seed + 2).choice(real_rows, 128, replace=False)
    ]
    real_oracle = ann_mod.exact_oracle(real_table, real_q, k)
    real_quant = ann_mod.build_index(real_table, "quant")
    real_ivf = ann_mod.build_index(real_table, "ivf", seed=seed)
    real = {"rows": real_rows, "dim": real_dim,
            "source": "synthetic-clustered@real-geometry"}
    for name, index in (("ivf", real_ivf), ("quant", real_quant)):
        found = np.empty((real_q.shape[0], k), np.int64)
        for s in range(0, real_q.shape[0], 64):
            _, idx = engine.top_k_ann(index, real_unit, real_q[s : s + 64], k)
            found[s : s + 64] = idx
        real[f"recall_at_10_{name}"] = round(
            ann_mod.recall_at_k(found, real_oracle), 4
        )
    log(f"real-geometry recall: {real}")

    return bench_stamp({
        "bench": "ann",
        "schema": "gene2vec-tpu/bench-ann/v1",
        "recipe": {
            "rows": rows, "dim": dim, "k": k, "queries": queries,
            "clusters": clusters, "nprobe": nprobe,
            "rescore_mult": rescore_mult, "seed": seed,
        },
        "modes": modes,
        "real_table": real,
        "ivf_index": ann_mod.index_stats(ivf),
    })


def quality_gate(dim: int, batch_pairs: int, data_dir: str) -> dict:
    """Verify the HEADLINE configuration learns before any throughput is
    reported (VERDICT round-2 item 3: a flat-loss run must not produce a
    headline number).

    Checks, at the same ``--dim``/``--batch`` the throughput number uses:

    * holdout link-prediction: SGNS at (dim, batch_pairs) on the canonical
      seen-gene protocol (gene2vec_tpu/eval/holdout.py); in-vocab cosine
      AUC >= GATE_MIN_AUC (frozen next to the oracle reference in that
      module), and its loss escapes the init plateau ln2·(1+K) (freeze
      guard).  This is the strongest check; when ``data_dir`` is missing
      it is recorded as SKIPPED — visibly, never as a silent pass.
    * planted clusters separate (collapse guard, thresholds frozen in
      gene2vec_tpu/eval/planted.py — QUALITY_NOTES §2 lists designs that
      pass any intra-only check while inter drifts to 0.97).  The planted
      corpus is 20k pairs, so the trainer auto-shrinks large batches; this
      check covers small-batch dynamics, the holdout check covers the
      headline batch size.
    """
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.eval.holdout import (
        GATE_MAX_AUC,
        ORACLE_COS_AUC,
        auc_in_gate_band,
        holdout_cos_auc,
        load_holdout,
    )
    from gene2vec_tpu.eval.planted import (
        INTER_MAX,
        INTRA_MIN,
        cluster_cosines,
        planted_corpus,
    )
    from gene2vec_tpu.sgns.train import train_epochs

    out = {}
    init_plateau = float(np.log(2.0) * (1 + SGNSConfig().negatives))

    def _fin(x, places):
        # round() propagates NaN, and json.dumps would then emit a literal
        # NaN token — invalid JSON on the one stdout line the contract
        # guarantees, on exactly the diverged run the gate exists to report
        return round(float(x), places) if np.isfinite(x) else "diverged"

    # -- strongest check: real-data holdout AUC at the HEADLINE config ----
    if os.path.isdir(data_dir):
        hcorpus, split = load_holdout(data_dir)
        emb, losses = train_epochs(
            hcorpus, SGNSConfig(dim=dim, batch_pairs=batch_pairs), 50
        )
        out["loss_first"] = _fin(losses[0], 4)
        out["loss_last"] = _fin(losses[-1], 4)
        out["loss_decreasing"] = bool(losses[-1] < init_plateau - 1.0)
        auc = (
            holdout_cos_auc(hcorpus.vocab, emb, split)
            if np.isfinite(emb).all()
            else float("nan")
        )
        out["holdout_cos_auc"] = _fin(auc, 4)
        out["holdout_oracle"] = ORACLE_COS_AUC
        # two-sided: far ABOVE the oracle is degeneration toward raw
        # co-occurrence, not quality (GATE_MAX_AUC note; QUALITY_NOTES §8)
        auc_ok = auc_in_gate_band(auc)
        if auc > GATE_MAX_AUC:
            out["auc_above_sanity_bound"] = GATE_MAX_AUC
    else:
        out["holdout_cos_auc"] = f"SKIPPED — {data_dir} not present"
        auc_ok = True  # recorded as skipped above, never a silent pass

    # -- collapse guard: planted clusters (small corpus, auto-shrunk batch)
    vocab, corpus = planted_corpus()
    emb, losses = train_epochs(
        corpus, SGNSConfig(dim=64, batch_pairs=min(batch_pairs, 1024)), 15
    )
    if "loss_decreasing" not in out:  # holdout check skipped
        out["loss_first"] = _fin(losses[0], 4)
        out["loss_last"] = _fin(losses[-1], 4)
        out["loss_decreasing"] = bool(losses[-1] < init_plateau - 1.0)

    if np.isfinite(emb).all():
        intra, inter = cluster_cosines(vocab, emb)
    else:
        intra = inter = float("nan")
    out["planted_intra"] = _fin(intra, 3)
    out["planted_inter"] = _fin(inter, 3)

    out["passed"] = bool(
        out["loss_decreasing"]
        and intra > INTRA_MIN
        and inter < INTER_MAX
        and auc_ok
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=200)
    ap.add_argument("--vocab", type=int, default=24447)  # reference gene count scale
    ap.add_argument("--pairs", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--cpu-pairs", type=int, default=200_000)
    ap.add_argument("--secondary-pairs", type=int, default=1_000_000)
    ap.add_argument("--no-secondary", action="store_true")
    ap.add_argument("--mesh-data", type=int, default=0,
                    help="run the headline data-parallel over the first N "
                    "attached devices (0 = single device); the result JSON "
                    "records the mesh shape and device count")
    ap.add_argument("--no-quality-gate", action="store_true",
                    help="skip the quality gate (exploration only; the "
                    "recorded headline must carry it)")
    ap.add_argument("--data-dir", default="/root/reference/predictionData",
                    help="reference predictionData for the gate's real-"
                    "data AUC check (recorded as SKIPPED when absent)")
    ap.add_argument("--run-dir", default=None,
                    help="observed-run directory (manifest.json + "
                    "events.jsonl + metrics.prom; summarize with "
                    "`python -m gene2vec_tpu.cli.obs report`); default "
                    "runs/bench_<unix-ts> next to this script")
    ap.add_argument("--timeline-overhead", action="store_true",
                    help="measure timeline-on vs timeline-off SGNS "
                    "throughput at the recipe pinned in budgets.json "
                    "'perf' and write --perf-out (the BENCH_PERF "
                    "artifact analysis/passes_perf.py gates); skips "
                    "the normal bench pipeline")
    ap.add_argument("--perf-out", default="BENCH_PERF_r10.json",
                    help="output path for --timeline-overhead")
    ap.add_argument("--ann", action="store_true",
                    help="run the approximate-retrieval scaling bench "
                    "(exact vs int8-quant vs IVF+int8 top-k, recall@10 "
                    "vs the exact numpy oracle, p50/p99 + bytes/query; "
                    "recipe defaults come from budgets.json 'ann'); "
                    "skips the normal bench pipeline; exits 1 when "
                    "recall falls below --ann-min-recall")
    ap.add_argument("--ann-rows", type=int, default=None,
                    help="synthetic table rows (default: the pinned "
                    "recipe's 1,000,000; the CI smoke uses 65536)")
    ap.add_argument("--ann-queries", type=int, default=None,
                    help="recall query count (default: recipe)")
    ap.add_argument("--ann-min-recall", type=float, default=0.99,
                    help="exit 1 when quant/ivf recall@10 lands below "
                    "this on either table")
    ap.add_argument("--ann-out", default="BENCH_ANN_r12.json",
                    help="output path for --ann")
    ap.add_argument("--kernel-profile", action="store_true",
                    help="attribute static XLA costs (flops/bytes/peak "
                    "memory + compile seconds) and timed achieved "
                    "throughput for every registered compute hot path "
                    "(SGNS/CBOW-HS/GGIPNN steps, serve top-k per index "
                    "mode, int8 ANN scan) at the recipe pinned in "
                    "budgets.json 'kernels', plus the profiling-overhead "
                    "windows, and write --kernels-out (the BENCH_KERNELS "
                    "artifact analysis/passes_kernels.py gates); skips "
                    "the normal bench pipeline")
    ap.add_argument("--kernels-out", default="BENCH_KERNELS_r18.json",
                    help="output path for --kernel-profile")
    args = ap.parse_args()

    if args.kernel_profile:
        from gene2vec_tpu.analysis.passes_hlo import load_budgets

        recipe = load_budgets().get("kernels", {}).get("profile", {})
        doc = kernel_profile_bench(recipe)
        with open(args.kernels_out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        log(f"wrote {args.kernels_out}")
        print(json.dumps(doc))
        return

    if args.ann:
        from gene2vec_tpu.analysis.passes_hlo import load_budgets

        recipe = load_budgets().get("ann", {}).get("recall", {}).get(
            "recipe", {}
        )
        rows = int(args.ann_rows or recipe.get("rows", 1_000_000))
        # the centroid count scales with the table when the smoke
        # shrinks rows off-recipe; on-recipe it is the pinned value
        clusters = int(recipe.get("clusters", 1024))
        if args.ann_rows and args.ann_rows != recipe.get("rows"):
            from gene2vec_tpu.serve.ann import default_clusters

            clusters = default_clusters(rows)
        doc = ann_bench(
            rows=rows,
            dim=int(recipe.get("dim", 64)),
            k=int(recipe.get("k", 10)),
            queries=int(args.ann_queries or recipe.get("queries", 512)),
            clusters=clusters,
            nprobe=int(recipe.get("nprobe", 32)),
            rescore_mult=int(recipe.get("rescore_mult", 4)),
            seed=int(recipe.get("seed", 0)),
        )
        floor = float(args.ann_min_recall)
        recalls = {
            "ivf": doc["modes"]["ivf"]["recall_at_10"],
            "quant": doc["modes"]["quant"]["recall_at_10"],
            "real_ivf": doc["real_table"]["recall_at_10_ivf"],
            "real_quant": doc["real_table"]["recall_at_10_quant"],
        }
        doc["min_recall_at_10"] = floor
        doc["passed"] = all(v >= floor for v in recalls.values())
        with open(args.ann_out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        log(f"wrote {args.ann_out}")
        print(json.dumps(doc))
        if not doc["passed"]:
            log(f"ANN recall gate FAILED: {recalls} < {floor}")
            sys.exit(1)
        return

    if args.timeline_overhead:
        from gene2vec_tpu.analysis.passes_hlo import load_budgets

        recipe = load_budgets().get("perf", {}).get("timeline_overhead", {})
        doc = timeline_overhead(
            dim=int(recipe.get("dim", 64)),
            vocab=int(recipe.get("vocab", 2048)),
            num_pairs=int(recipe.get("num_pairs", 65536)),
            batch_pairs=int(recipe.get("batch_pairs", 2048)),
            rounds=int(recipe.get("rounds", 5)),
            epochs_per_window=int(recipe.get("epochs_per_window", 2)),
        )
        with open(args.perf_out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        log(f"wrote {args.perf_out}")
        print(json.dumps(doc))
        return

    from gene2vec_tpu.obs.run import Run

    run_dir = args.run_dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "runs", f"bench_{int(time.time())}",
    )
    # probe_devices=False at construction: the dedicated-process probes
    # below must see an untouched chip; backend facts are annotated after
    # this process first initializes jax anyway.
    run = Run(run_dir, name="bench", config=vars(args), probe_devices=False)
    # in-process _steady_rate calls record dispatch/compute phases into
    # the module timeline, flushed into the run dir at exit (the
    # dedicated-process probes keep it disabled in their interpreter)
    tl = _bench_timeline()
    tl.enabled = True
    try:
        log(f"observed run dir: {run_dir}")

        # bf16-table opt-in probe FIRST: it needs the chip to itself, before
        # this process initializes its own TPU client (bf16_table_probe doc).
        # Measured at the HEADLINE corpus/batch so the number reads as
        # "the headline config with bf16 tables" — NOT at secondary_pairs.
        # Skipped under --mesh-data: the device-count check below must claim
        # the chips first, and a probe sharing them reads ~35% low.
        bf16_rate = None
        headline = None
        if args.mesh_data == 0:
            # headline FIRST (cleanest device state), then the bf16 probe
            with run.span("headline_probe"):
                headline = headline_probe(
                    args.dim, args.vocab, args.pairs, args.batch
                )
            if not args.no_secondary:
                with run.span("bf16_table_probe"):
                    bf16_rate = bf16_table_probe(
                        args.vocab, args.pairs, args.batch
                    )
        elif args.mesh_data > 0:
            log("dedicated-process probes skipped under --mesh-data (the "
                "device-count check below must claim the chips first)")

        if args.mesh_data > 0:
            # fail in seconds, not after the multi-minute quality gate
            import jax

            n = len(jax.devices())
            if args.mesh_data > n:
                raise SystemExit(
                    f"--mesh-data {args.mesh_data}: only {n} device(s) attached"
                )

        quality = {}
        if not args.no_quality_gate:
            log("=== quality gate (headline config must learn) ===")
            with run.span("quality_gate") as span_out:
                quality = quality_gate(args.dim, args.batch, args.data_dir)
                span_out["passed"] = quality["passed"]
            log(f"quality: {quality}")
            if not quality["passed"]:
                # No headline for a trainer that does not learn (round-2
                # verdict: "fast and wrong is wrong").
                run.event("quality_gate_failed", **{
                    k: v for k, v in quality.items() if not isinstance(v, dict)
                })
                run.close()
                print(json.dumps({
                    "metric": "sgns_pairs_per_sec",
                    "value": 0.0,
                    "unit": "pairs/s",
                    "vs_baseline": 0.0,
                    "quality": quality,
                    "error": "quality gate FAILED — throughput withheld",
                }))
                sys.exit(1)

        if headline is not None:
            tpu_rate, band = headline
            import jax

            mesh_info = {
                "devices": 1,
                "platform": jax.devices()[0].platform,
                "mesh": None,
                "rate_band": band,
            }
        else:
            with run.span("measure_headline_in_process"):
                tpu_rate, mesh_info = measure_pairs_per_sec(
                    args.dim, args.vocab, args.pairs, args.batch, args.mesh_data
                )
        run.annotate(backend={
            "platform": mesh_info["platform"],
            "device_count": mesh_info["devices"],
            "mesh": mesh_info["mesh"],
        })
        run.probe()

        vs = vs32 = base1 = None
        extrapolated = None
        try:
            with run.span("hogwild_baseline"):
                cpu_best, cpu_1core, curve = hogwild_baseline(
                    args.dim, args.vocab, args.cpu_pairs
                )
            base1 = cpu_1core
            vs = tpu_rate / cpu_best
            # Linear 32-thread extrapolation from the measured per-core rate —
            # an upper bound on Hogwild scaling, hence a conservative speedup.
            vs32 = tpu_rate / (32.0 * cpu_1core)
            # the denominator is synthetic unless 32 threads were actually run
            # (VERDICT r3 item 7: the ratio must not be quotable as measured;
            # a >32-core host still never measures the 32-thread point unless
            # it is in the curve)
            extrapolated = 32 not in curve
            log(f"hogwild curve: {curve}; 32-thread linear extrapolation "
                f"{32.0 * cpu_1core:,.0f} pairs/s"
                + (" (EXTRAPOLATED from fewer cores)" if extrapolated else ""))
        except Exception as e:
            log(f"hogwild baseline failed: {e}")

        secondary = {}
        if not args.no_secondary:
            with run.span("secondary_metrics"):
                secondary = secondary_metrics(
                    args.vocab, args.secondary_pairs, args.batch
                )
            if bf16_rate is not None:
                secondary["table_bf16_pairs_per_sec"] = bf16_rate
                # unlike the other secondaries (measured at secondary_pairs),
                # this one is the HEADLINE workload with bf16 tables — the
                # comparison the opt-in claim is about
                secondary["table_bf16_note"] = (
                    "headline corpus/batch, dedicated process"
                )
            try:
                with open(
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_EXTRA.json"), "w"
                ) as f:
                    json.dump(bench_stamp(dict(secondary)), f, indent=1)
            except OSError as e:
                log(f"could not write BENCH_EXTRA.json: {e}")

        result = bench_stamp({
            "metric": "sgns_pairs_per_sec",
            "value": round(tpu_rate, 1),
            "unit": "pairs/s",
            # the measured min..max of this run's timed epochs: quote ratios
            # as bands — numerator AND the extrapolated CPU denominator carry
            # run-to-run noise (README "honest position" table is sourced
            # from these fields, VERDICT r4 number-hygiene item)
            "rate_band": mesh_info.get("rate_band"),
            "vs_baseline": round(vs, 2) if vs else None,
            "vs_32thread_equiv": round(vs32, 2) if vs32 else None,
            "vs_32thread_equiv_extrapolated": extrapolated,
            "baseline_1core": round(base1, 1) if base1 else None,
            "platform": mesh_info["platform"],
            "devices": mesh_info["devices"],
            "mesh": mesh_info["mesh"],
        })
        if quality:
            result["quality"] = quality
        if secondary:
            result["secondary"] = secondary
        run.event(
            "bench_result",
            **{k: v for k, v in result.items() if not isinstance(v, dict)},
        )
        run.registry.gauge("sgns_pairs_per_sec").set(tpu_rate)
        run.probe()
        print(json.dumps(result))
    finally:
        # error exits (device-count SystemExit, probe failures) must
        # still terminate the observed run — run_end + metrics.prom —
        # exactly like the trainers' try/finally
        import contextlib as _ctx

        with _ctx.suppress(Exception):
            from gene2vec_tpu.obs.timeline import TIMELINE_NAME

            tl.flush(os.path.join(run_dir, TIMELINE_NAME))
        run.close()


if __name__ == "__main__":
    main()
