#!/usr/bin/env bash
# graftcheck driver: lint passes + HLO budget checks (+ optional
# sanitizer parity runs).  Nonzero exit on any gating finding.
#
# Coverage spans every compiled hot path: the SGNS/CBOW-HS epochs, the
# GGIPNN train step, and the serve/ top-k engine (host-callback + dtype
# + bucketed jit-cache-stability via `--hlo hot`; the row-sharded
# engine's per-query collective-bytes ceiling via `--hlo budgets`,
# budgets.json section "serve").  The default tier also runs the
# span-hygiene pass (no obs span enter/exit inside jitted/traced code,
# no span context manager left unclosed on early return), the
# concurrency tier (threadflow role inference: lock-discipline,
# loop-thread-blocking, blocking-while-locked, lock-order — all four in
# --fast; docs/STATIC_ANALYSIS.md#concurrency-tier), the dead-budget
# lint (budget-lint: stale budgets.json keys / unanchored gating
# passes), and the committed-bench budget gates: fleet availability (BENCH_FLEET vs
# budgets.json "fleet"), tracing overhead (BENCH_OBS vs "obs"), and the
# perf plane (BENCH_PERF timeline overhead + unified-ledger trajectory
# regressions vs "perf"; docs/BENCHMARKS.md).  The ledger ingest +
# regression check also runs standalone below so its rendered
# trajectory lands in the CI log.
#
#   scripts/run_static_analysis.sh                 # lint + tier-2 HLO
#   scripts/run_static_analysis.sh --fast          # lint only (tier-1 scope)
#   scripts/run_static_analysis.sh --with-sanitizers   # + asan,ubsan,tsan
#   scripts/run_static_analysis.sh --with-chaos    # + the resilience chaos
#                                                  # smoke drill (kill/resume
#                                                  # bit-exactness, torn-export
#                                                  # no-swap, async-ckpt
#                                                  # budget, the fleet
#                                                  # smoke: 3 replicas, one
#                                                  # SIGKILLed + one fault-
#                                                  # injected under closed-loop
#                                                  # load, availability gated
#                                                  # by budgets.json "fleet";
#                                                  # AND the alert-detection
#                                                  # smoke: one injected fault
#                                                  # -> the availability rule
#                                                  # fires within budget, zero
#                                                  # warmup false positives,
#                                                  # incident bundle verified;
#                                                  # AND the autoscale smoke:
#                                                  # 1 -> 2 -> 1 replicas
#                                                  # under a short ramp with
#                                                  # a zero-drop drain and
#                                                  # abusive-tenant isolation;
#                                                  # AND the loop smoke: a
#                                                  # full continuous-learning
#                                                  # cycle (ingest -> warm
#                                                  # start -> quality gate ->
#                                                  # shadow -> promote) with a
#                                                  # SIGKILL in every state;
#                                                  # AND the batch smoke: a
#                                                  # kNN graph job via
#                                                  # /v1/jobs on a live
#                                                  # sharded fleet, SIGKILL
#                                                  # mid-build -> bit-exact
#                                                  # resume, mixed-load p99
#                                                  # delta gated; AND the
#                                                  # catalog smoke: a two-
#                                                  # model fleet hot-swap +
#                                                  # per-model scale-up with
#                                                  # zero cross-model answers;
#                                                  # docs/BATCH.md +
#                                                  # docs/RESILIENCE.md +
#                                                  # docs/OBSERVABILITY.md +
#                                                  # docs/SERVING.md +
#                                                  # docs/CONTINUOUS.md)
#   scripts/run_static_analysis.sh --tsan-raw      # unsuppressed TSAN run
#                                                  # (expect intended-race
#                                                  # reports; for auditing
#                                                  # native/tsan.supp)
#
# The fast AST passes also run inside tier-1 (tests/test_analysis.py);
# the HLO/sanitizer tiers are the `slow`/`sanitizer`-marked tests
# (tests/test_analysis_hlo.py, tests/test_sanitizers.py).
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="full"
SAN=""
CHAOS=0
for arg in "$@"; do
  case "$arg" in
    --fast) MODE="fast" ;;
    --with-sanitizers) SAN="asan,ubsan,tsan" ;;
    --with-chaos) CHAOS=1 ;;
    --tsan-raw)
      make -C native tsan
      echo "== unsuppressed TSAN Hogwild run (intended races WILL report) ==" >&2
      GRAFTCHECK_SMALL=1 python - <<'EOF'
from gene2vec_tpu.analysis.sanitize import run_parity
import sys
p = run_parity("tsan", options="halt_on_error=0")
races = p.stderr.count("WARNING: ThreadSanitizer: data race")
print(f"tsan raw run: exit {p.returncode}, {races} race report(s)",
      file=sys.stderr)
EOF
      exit 0
      ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

ARGS=(--json)
if [ "$MODE" = "full" ]; then
  ARGS+=(--hlo all)
fi
if [ -n "$SAN" ]; then
  ARGS+=(--sanitizers "$SAN")
fi

OUT="${GRAFTCHECK_OUT:-/tmp/graftcheck_findings.json}"
if python -m gene2vec_tpu.cli.analyze "${ARGS[@]}" > "$OUT"; then
  rc=0
else
  rc=$?
fi
python - "$OUT" "$rc" <<'EOF'
import json, sys
rc = int(sys.argv[2])
try:
    with open(sys.argv[1]) as f:
        doc = json.load(f)
except (OSError, ValueError):
    # analyzer died before emitting JSON (stdout was redirected into
    # $OUT, so it is empty/truncated) — report THAT, preserving the
    # analyzer's exit code, instead of tracebacking on the decode
    print(
        f"graftcheck: analyzer crashed before emitting findings JSON "
        f"(exit {rc}); its stderr above is the real error",
        file=sys.stderr,
    )
    sys.exit(rc or 2)
s = doc["summary"]
print(f"graftcheck: {s['gating']} gating / {s['total']} total finding(s) "
      f"-> {sys.argv[1]}", file=sys.stderr)
by_pass = s.get("by_pass", {})
if by_pass:
    counts = " ".join(f"{k}={v}" for k, v in sorted(by_pass.items()))
    print(f"graftcheck: per-pass counts: {counts}", file=sys.stderr)
for f in doc["findings"]:
    if f["severity"] != "info":
        loc = f"{f['path']}:{f['line']}" if f.get("line") else f["path"]
        print(f"  {loc}: [{f['pass']}] {f['message']}", file=sys.stderr)
EOF
if [ "$rc" -ne 0 ]; then
  exit "$rc"
fi

# Unified bench ledger: ingest every root bench artifact and run the
# trailing-window regression rules (budgets.json "perf").  The analyzer
# above already gates on the same rules (passes_perf rides the default
# tier); this standalone run renders the full trajectory into the CI
# log and persists the ledger for tooling.
echo "== bench ledger (ingest + regression check) ==" >&2
LEDGER_OUT="${LEDGER_OUT:-/tmp/bench_ledger.jsonl}"
LEDGER_CSV="${LEDGER_CSV:-/tmp/bench_ledger.csv}"
python -m gene2vec_tpu.cli.obs ledger --check \
  --out "$LEDGER_OUT" --csv "$LEDGER_CSV" >&2 || rc=$?
echo "ledger: exit $rc -> $LEDGER_OUT / $LEDGER_CSV" >&2
if [ "$rc" -ne 0 ]; then
  exit "$rc"
fi

if [ "$CHAOS" = "1" ]; then
  echo "== chaos smoke drill (scripts/chaos_drill.py --smoke; incl. the" >&2
  echo "   fleet phase: replica kill + fault injection under load, and" >&2
  echo "   the alerts phase: injected fault -> rule fires -> incident" >&2
  echo "   bundle CRC-verified with a trace through the faulty replica) ==" >&2
  CHAOS_OUT="${CHAOS_DRILL_OUT:-/tmp/chaos_drill_smoke.json}"
  # the fleet/alerts/autoscale results also land in standalone bench
  # documents so the analyzer's gates can be refreshed from CI runs
  # (the committed BENCH_FLEET/BENCH_ALERTS/BENCH_AUTOSCALE records
  # come from the full, non-smoke drill).  The autoscale phase IS the
  # reduced-scale elasticity smoke: a 1 -> 2 -> 1 replica cycle under
  # a short ramp, zero-drop drain verified, plus the abusive-tenant
  # isolation check (docs/SERVING.md#elastic-fleet).
  FLEET_OUT="${FLEET_DRILL_OUT:-/tmp/chaos_drill_fleet_smoke.json}"
  ALERTS_OUT="${ALERTS_DRILL_OUT:-/tmp/chaos_drill_alerts_smoke.json}"
  AUTOSCALE_OUT="${AUTOSCALE_DRILL_OUT:-/tmp/chaos_drill_autoscale_smoke.json}"
  # the shard phase IS the reduced-size sharded-serving smoke: a 64k-
  # row scatter-merge bench (4 shards) plus a 2-shard fleet with one
  # SIGKILL mid-load, a swap-under-load, and a slow-loris shard (the
  # committed BENCH_SHARD record comes from the full, non-smoke drill)
  SHARD_OUT="${SHARD_DRILL_OUT:-/tmp/chaos_drill_shard_smoke.json}"
  # the loop phase IS the continuous-learning smoke: a full
  # ingest -> warm-start -> quality gate -> shadow canary -> promote
  # cycle against a real 2-replica fleet with a SIGKILL injected in
  # every loop state and bit-exact resume asserted against an
  # uninterrupted control (docs/CONTINUOUS.md)
  LOOP_OUT="${LOOP_DRILL_OUT:-/tmp/chaos_drill_loop_smoke.json}"
  # the batch phase IS the background-analytics smoke: a kNN graph job
  # submitted through /v1/jobs on a live 2-shard fleet, SIGKILLed mid-
  # build and resumed bit-identically, a mixed interactive+batch load
  # window gated on the interactive p99 delta, and a reduced-scale IVF
  # graph pass (the committed BENCH_BATCH record comes from the full,
  # non-smoke drill; docs/BATCH.md)
  BATCH_OUT="${BATCH_DRILL_OUT:-/tmp/chaos_drill_batch_smoke.json}"
  # the catalog phase is the multi-model smoke: a two-model --catalog
  # fleet hot-swaps its default model under verified load on both
  # models, then ramps the second model and proves only that model's
  # pool scales — 0 wrong/mixed/cross-model answers gated
  # (docs/SERVING.md#multi-model-catalog)
  CATALOG_OUT="${CATALOG_DRILL_OUT:-/tmp/chaos_drill_catalog_smoke.json}"
  python scripts/chaos_drill.py --smoke --fleet-out "$FLEET_OUT" \
    --alerts-out "$ALERTS_OUT" --autoscale-out "$AUTOSCALE_OUT" \
    --shard-out "$SHARD_OUT" --loop-out "$LOOP_OUT" \
    --batch-out "$BATCH_OUT" --catalog-out "$CATALOG_OUT" \
    > "$CHAOS_OUT" || rc=$?
  echo "chaos drill: exit $rc -> $CHAOS_OUT (fleet: $FLEET_OUT," >&2
  echo "  alerts: $ALERTS_OUT, autoscale: $AUTOSCALE_OUT," >&2
  echo "  shard: $SHARD_OUT, loop: $LOOP_OUT, batch: $BATCH_OUT," >&2
  echo "  catalog: $CATALOG_OUT)" >&2
  if [ "$rc" -ne 0 ]; then
    exit "$rc"
  fi

  # Serve capacity smoke: the event-loop front end must sustain a
  # REDUCED rps level (CI hosts are noisy; the full 600+ rps gate runs
  # against the committed BENCH_SERVE record via cli.analyze above).
  # Same recipe shape as the committed bench — open-loop GET, keep-
  # alive, capacity verdict at p99 <= 50 ms — just a smaller level and
  # window, asserted directly by the loadgen's exit code.
  echo "== serve capacity smoke (event-loop front end, reduced rps) ==" >&2
  SMOKE_EXPORT="${CAPACITY_SMOKE_EXPORT:-/tmp/capacity_smoke_export}"
  SMOKE_OUT="${CAPACITY_SMOKE_OUT:-/tmp/capacity_smoke.json}"
  SMOKE_RPS="${CAPACITY_SMOKE_RPS:-120}"
  JAX_PLATFORMS=cpu python - "$SMOKE_EXPORT" <<'EOF'
import os, sys
import numpy as np
import jax.numpy as jnp
from gene2vec_tpu.io.checkpoint import save_iteration
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.model import SGNSParams
d = sys.argv[1]
os.makedirs(d, exist_ok=True)
V, D = 512, 16
rng = np.random.RandomState(0)
save_iteration(
    d, D, 1,
    SGNSParams(emb=jnp.asarray(rng.randn(V, D).astype(np.float32)),
               ctx=jnp.asarray(np.zeros((V, D), np.float32))),
    Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1)),
)
print(f"capacity smoke export ready: {d}", file=sys.stderr)
EOF
  JAX_PLATFORMS=cpu python scripts/serve_loadgen.py \
    --spawn "$SMOKE_EXPORT" --method get --mode open \
    --levels "$SMOKE_RPS" --duration 3 --num-genes 64 \
    --assert-capacity "$SMOKE_RPS" \
    --output "$SMOKE_OUT" > /dev/null || rc=$?
  echo "capacity smoke: exit $rc -> $SMOKE_OUT" >&2
  if [ "$rc" -ne 0 ]; then
    exit "$rc"
  fi

  # ANN recall smoke: the IVF+int8+exact-rescore retrieval path must
  # hold recall@10 >= 0.99 vs the exact numpy oracle at a REDUCED table
  # size (64k synthetic rows; the full 1M-row gate runs against the
  # committed BENCH_ANN record via cli.analyze above).  Same recipe
  # shape as the committed bench — clustered table, table-row queries,
  # pinned nprobe/rescore — asserted directly by the bench's exit code.
  echo "== ANN recall smoke (IVF+int8 retrieval, 64k rows) ==" >&2
  ANN_SMOKE_OUT="${ANN_SMOKE_OUT:-/tmp/ann_recall_smoke.json}"
  JAX_PLATFORMS=cpu python bench.py --ann \
    --ann-rows 65536 --ann-queries 128 --ann-min-recall 0.99 \
    --ann-out "$ANN_SMOKE_OUT" > /dev/null || rc=$?
  echo "ann smoke: exit $rc -> $ANN_SMOKE_OUT" >&2
fi
exit "$rc"
