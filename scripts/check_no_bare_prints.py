"""Thin shim: the no-bare-print lint now lives in the graftcheck pass
framework (``gene2vec_tpu.analysis.passes_ast.BarePrintPass``), where it
also covers ``experiments/``.  This script keeps the original CLI and
function surface (``bare_prints_in_source`` / ``check_tree``) so existing
wiring — tests/test_obs.py, docs, muscle memory — keeps working.

Run: ``python scripts/check_no_bare_prints.py [root]`` — exits non-zero
listing violations.  Equivalent: ``python -m gene2vec_tpu.cli.analyze
--select bare-print``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from gene2vec_tpu.analysis.astpass import ModuleSource  # noqa: E402
from gene2vec_tpu.analysis.passes_ast import BarePrintPass  # noqa: E402
from gene2vec_tpu.analysis.runner import suppressed  # noqa: E402

_PASS = BarePrintPass()


def bare_prints_in_source(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, line) for every ``print(...)`` call without ``file=``.
    Honors ``# graftcheck: disable=bare-print`` like every other entry
    point (the pragma must mean the same thing in the shim and the CLI)."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    mod = ModuleSource(
        filename, filename, source, tree, source.splitlines()
    )
    return [
        (f.line, f.snippet)
        for f in _PASS.run(mod)
        if not suppressed(mod, f)
    ]


def check_tree(pkg_root: str) -> List[str]:
    """Violation strings for every library module under ``pkg_root``
    (the ``gene2vec_tpu`` package dir), skipping the CLI layer."""
    from gene2vec_tpu.analysis.astpass import iter_py_files

    repo_root = os.path.dirname(os.path.abspath(pkg_root))
    violations = []
    for path in iter_py_files(pkg_root):
        rel = os.path.relpath(path, repo_root)
        if not _PASS.applies(rel):
            continue
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        for lineno, line in bare_prints_in_source(source, path):
            violations.append(f"{rel}:{lineno}: {line}")
    return violations


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        violations = check_tree(argv[0])
    else:
        # no explicit root: the full pass (package + experiments/)
        from gene2vec_tpu.analysis import run_ast_passes

        violations = [
            f"{f.path}:{f.line}: {f.snippet}"
            for f in run_ast_passes(select=["bare-print"])
        ]
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} bare print() call(s) in library code — "
            "route through gene2vec_tpu.obs, a log callable, or an "
            "explicit file= stream",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
