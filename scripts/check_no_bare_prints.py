"""Lint: no bare ``print()`` in gene2vec_tpu/ library code.

Library modules must emit through the observability layer
(``gene2vec_tpu.obs``), an injected ``log`` callable, or an explicit
stream (``print(..., file=sys.stderr)``) — a bare ``print`` call writes
to stdout, which CLI contracts own (bench.py prints exactly ONE JSON
line on stdout; a stray library print corrupts it).

Allowed:

* anything under ``gene2vec_tpu/cli/`` — the CLI layer owns stdout;
* ``print(..., file=...)`` calls — the stream choice is explicit;
* referencing ``print`` without calling it (the ``log: Callable = print``
  default-argument idiom).

Run: ``python scripts/check_no_bare_prints.py [root]`` — exits non-zero
listing violations.  Wired into tier-1 via tests/test_obs.py.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple


def bare_prints_in_source(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, line) for every ``print(...)`` call without ``file=``."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Name) and fn.id == "print"):
            continue
        if any(kw.arg == "file" for kw in node.keywords):
            continue
        line = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
        out.append((node.lineno, line))
    return out


def check_tree(pkg_root: str) -> List[str]:
    """Violation strings for every library module under ``pkg_root``
    (the ``gene2vec_tpu`` package dir), skipping the CLI layer."""
    violations = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if os.path.basename(dirpath) == "cli":
            dirnames[:] = []
            continue
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            for lineno, line in bare_prints_in_source(source, path):
                violations.append(f"{rel}:{lineno}: {line}")
    return violations


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "gene2vec_tpu",
    )
    violations = check_tree(root)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} bare print() call(s) in library code — "
            "route through gene2vec_tpu.obs, a log callable, or an "
            "explicit file= stream",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
