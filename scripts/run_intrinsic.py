"""Real-data intrinsic score via the reference's target function
(VERDICT r3 item 4): pathway-ratio for the real-corpus-trained embedding
vs a random table, written to INTRINSIC_r05.json.

**Pathway source & limitation (documented, not hidden).**  The canonical
input is MSigDB v6.1 (``src/evaluation_target_function.py:54-60``), which
the reference does not bundle and which is unobtainable here (zero
package/data egress — see docs/QUALITY_NOTES.md §5 for the recorded
attempt).  The best independent gene-set source in this environment is
the reference's own predictionData: we build sets from HELD-OUT positive
pairs (the canonical eval.holdout split — the same 20%/seed-7 holdout the
AUC protocol scores; the embedding never trains on them).  Each set is a
gene's held-out positive neighborhood (its partners across held-out
pairs), sizes 2..50 to match the reference's ≤50-gene pathway filter.
Genes sharing interaction partners are functionally related, so a real
embedding must score intra-set cosine ≫ random-pair cosine — exactly the
target function's contract.  The sets are written as a genuine ``.gmt``
file and scored through the UNCHANGED ``target_function`` entry point
(gmt parsing, ≤50-gene filter, seed-35 shuffled denominator all
exercised).

Controls, and why the headline is reported as raw numerator/denominator
pairs and not only the reference's ratio: for a RANDOM table both the
intra-set mean and the seed-35 random-pair mean are ≈ 0, so their ratio
is noise amplification (a first run measured 2.15 for a random table —
meaningless, both terms ~5e-3).  The informative comparisons are

* trained, real sets vs trained, SIZE-MATCHED random sets — same
  geometry, same set-size distribution, only the biology removed; the
  gap is what the embedding knows about held-out interactions;
* trained vs random-table raw intra-set cosine — geometry vs none;
* the reference-exact ratio (``targetFunc``) for the trained embedding,
  which is the number comparable to reference-pipeline outputs.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from collections import defaultdict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gene2vec_tpu.config import SGNSConfig  # noqa: E402
from gene2vec_tpu.eval.holdout import load_holdout  # noqa: E402
from gene2vec_tpu.eval.target_function import (  # noqa: E402
    pathway_similarities,
    random_pair_similarity,
    target_function,
)
from gene2vec_tpu.io.emb_io import write_word2vec_format  # noqa: E402
from gene2vec_tpu.sgns.train import train_epochs  # noqa: E402

DATA_DIR = "/root/reference/predictionData"
MAX_SET = 50
MIN_SET = 2


def neighborhood_sets(hold_pairs, hold_labels, vocab):
    """gene -> sorted list of its held-out positive partners (in-vocab),
    capped at MAX_SET."""
    labels = np.asarray(hold_labels)
    nbrs = defaultdict(set)
    for (a, b), y in zip(hold_pairs, labels):
        if y != 1:
            continue
        if a in vocab.token_to_id and b in vocab.token_to_id:
            nbrs[a].add(b)
            nbrs[b].add(a)
    sets = {}
    for g, partners in nbrs.items():
        partners = sorted(partners)[:MAX_SET]
        if len(partners) >= MIN_SET:
            sets[f"HOLDOUT_NBR_{g}"] = partners
    return sets


def write_gmt(path, sets):
    with open(path, "w") as f:
        for name, genes in sets.items():
            f.write("\t".join([name, "holdout://predictionData"] + genes) + "\n")


def main():
    corpus, split = load_holdout(DATA_DIR)
    vocab = corpus.vocab
    print(
        f"corpus {corpus.num_pairs} pairs, vocab {len(vocab)}; "
        f"holdout {len(split.hold_pairs)} pairs",
        file=sys.stderr, flush=True,
    )

    t0 = time.perf_counter()
    emb, losses = train_epochs(
        corpus, SGNSConfig(dim=200, batch_pairs=4096), 50
    )
    train_s = time.perf_counter() - t0

    sets = neighborhood_sets(split.hold_pairs, split.hold_labels, vocab)
    # size-matched random sets: same size multiset, genes drawn uniformly
    # from the vocab — removes the biology, keeps every set-size artifact
    rng = np.random.RandomState(0)
    all_tokens = np.asarray(vocab.id_to_token)
    matched = {
        f"MATCHED_{i}": list(
            all_tokens[rng.choice(len(vocab), size=len(g), replace=False)]
        )
        for i, g in enumerate(sets.values())
    }
    rng = np.random.RandomState(1)
    random_table = rng.uniform(-0.25, 0.25, emb.shape).astype(np.float32)

    out = {
        "protocol": {
            "pathway_source": (
                "held-out positive-pair neighborhoods from the canonical "
                "eval.holdout split (MSigDB v6.1 unobtainable: zero "
                "egress, attempt recorded in docs/QUALITY_NOTES.md §5); "
                "sets never seen by the embedding"
            ),
            "n_sets": len(sets),
            "set_size_filter": [MIN_SET, MAX_SET],
            "embedding": "SGNS default config, dim 200, 50 epochs, B=4096",
            "sgns_loss": [round(losses[0], 4), round(losses[-1], 4)],
            "train_seconds": round(train_s, 1),
        }
    }
    tokens = list(vocab.id_to_token)
    with tempfile.TemporaryDirectory() as tmp:
        # the reference-exact entry point (gmt parse, <=50 filter,
        # seed-35 denominator) for the number comparable to the
        # reference pipeline's targetFunc output
        gmt = os.path.join(tmp, "holdout_sets.gmt")
        write_gmt(gmt, sets)
        trained_w2v = os.path.join(tmp, "trained_w2v.txt")
        write_word2vec_format(trained_w2v, tokens, emb)
        out["trained_target_func_ratio"] = round(
            target_function(trained_w2v, gmt), 4
        )

    num_real, _ = pathway_similarities(tokens, emb, sets)
    num_matched, _ = pathway_similarities(tokens, emb, matched)
    denom = random_pair_similarity(tokens, emb)
    rnum_real, _ = pathway_similarities(tokens, random_table, sets)
    rnum_matched, _ = pathway_similarities(tokens, random_table, matched)
    rdenom = random_pair_similarity(tokens, random_table)
    out["trained"] = {
        "intra_set_cos_real_sets": round(num_real, 4),
        "intra_set_cos_size_matched_random_sets": round(num_matched, 4),
        "random_pair_cos": round(denom, 4),
    }
    out["random_table"] = {
        "intra_set_cos_real_sets": round(rnum_real, 4),
        "intra_set_cos_size_matched_random_sets": round(rnum_matched, 4),
        "random_pair_cos": round(rdenom, 4),
        "note": "all ~0: no geometry — the targetFunc RATIO of two "
                "near-zero terms is undefined noise for a random table, "
                "which is why raw terms are recorded",
    }
    out["interpretation"] = (
        "the embedding knows held-out biology iff "
        "trained.intra_set_cos_real_sets >> "
        "trained.intra_set_cos_size_matched_random_sets (same geometry, "
        "same set sizes, biology removed) and >> "
        "random_table.intra_set_cos_real_sets (no geometry at all); "
        "trained_target_func_ratio is the reference-comparable number."
    )
    # provenance stamp (the ledger contract, docs/BENCHMARKS.md): the
    # committed INTRINSIC_* record must not ingest as legacy_unstamped
    from bench import bench_stamp

    bench_stamp(out)
    with open(os.path.join(REPO, "INTRINSIC_r05.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
