"""Config-5 communication audit without hardware (VERDICT r4 item 7).

Compiles the 8-way vocab-sharded training epoch (BASELINE config 5:
dim=512, row-parallel tables over the model axis) on the forced-8-device
CPU backend, then counts and sizes every collective in the optimized HLO.
The per-step collective budget — the scan body appears once in the module
— gives a bytes-per-pair communication model that predicts what a real
v5e-8 would move over ICI (written up in docs/PERF_NOTES.md round 5).

Run: python scripts/hlo_comm_audit.py [--dim 512] [--batch 16384]
Writes experiments/results/hlo_comm_r5.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# sitecustomize latches env vars before we run — re-pin via the config API
# (tests/conftest.py pattern; axon-tunnel memory note)
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # pre-0.5 jax has no such option; the XLA flag is read at backend
    # initialization, which hasn't happened yet
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

from bench import synth_corpus  # noqa: E402
from gene2vec_tpu.config import MeshConfig, SGNSConfig  # noqa: E402
from gene2vec_tpu.obs.probes import collective_stats  # noqa: E402
from gene2vec_tpu.parallel.mesh import make_mesh  # noqa: E402
from gene2vec_tpu.parallel.sharding import SGNSSharding  # noqa: E402
from gene2vec_tpu.sgns.train import SGNSTrainer  # noqa: E402


def audit(dim: int, vocab: int, batch: int, num_pairs: int, mid: bool,
          vocab_sharded: bool = True):
    """The HLO collective scan itself lives in
    ``gene2vec_tpu.obs.probes.collective_stats`` so trainers can record
    their comm budget per run; this script is the standalone CLI."""
    corpus = synth_corpus(vocab, num_pairs)
    if vocab_sharded:
        mesh = make_mesh(MeshConfig(data=1, model=8))
    else:
        mesh = make_mesh(MeshConfig(data=8, model=1))
    cfg = SGNSConfig(
        dim=dim, batch_pairs=batch, vocab_sharded=vocab_sharded,
        positive_mid=2048 if mid else 0,
    )
    trainer = SGNSTrainer(
        corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=vocab_sharded)
    )
    params = trainer.init()
    lowered = trainer._epoch_fn.lower(
        params, trainer.pairs, trainer.noise, jax.random.PRNGKey(0)
    )
    stats = collective_stats(lowered)
    if stats is None:
        # collective_stats swallows exceptions so trainers can probe
        # unconditionally; in this standalone audit a silent None would
        # just crash below with an opaque TypeError — fail loudly instead.
        raise RuntimeError(
            f"HLO collective audit failed to compile/scan config "
            f"dim={dim} batch={batch} vocab_sharded={vocab_sharded}"
        )

    return {
        "config": {
            "dim": dim, "vocab": vocab, "batch_pairs": batch,
            "mesh": (
                "1x8 (model=8, vocab-sharded)"
                if vocab_sharded
                else "8x1 (data=8, replicated tables)"
            ),
            "positive_mid": cfg.positive_mid,
            "positive_head": cfg.positive_head,
        },
        "collectives_per_step": stats["collectives"],
        "total_bytes_per_step": stats["total_bytes"],
        "bytes_per_pair": round(stats["total_bytes"] / batch, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=24447)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--pairs", type=int, default=131072)  # compile-only scale
    args = ap.parse_args()

    out = {
        "with_dense_slabs": audit(
            args.dim, args.vocab, args.batch, args.pairs, mid=True
        ),
        "plain_gather_round4": audit(
            args.dim, args.vocab, args.batch, args.pairs, mid=False
        ),
        "data_parallel_8way": audit(
            args.dim, args.vocab, args.batch, args.pairs, mid=True,
            vocab_sharded=False,
        ),
    }
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "experiments", "results", "hlo_comm_r5.json",
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
