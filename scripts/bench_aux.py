"""Viz- and corpus-layer benchmarks (VERDICT r3 item 5): the
"matching-or-beating on perf" claim measured for L5 (t-SNE) and L1
(corpus correlation), not just the SGNS hot loop.  Writes
AUX_BENCH_r04.json at the repo root.

(a) t-SNE — the reference's single heaviest native dependency is
MulticoreTSNE (C++/OpenMP Barnes-Hut, ``src/tsne_multi_core.py:42-52``:
perplexity 30, lr 200, n_jobs=32, runs up to 100k iterations on ~24k
genes x 200d after PCA-50).  MulticoreTSNE is not installed here, so the
CPU denominator is sklearn's Barnes-Hut TSNE (same algorithm family) on
this host, with a LINEAR 32-thread extrapolation recorded as
``extrapolated: true`` (generous to the CPU: BH-tSNE's tree build does
not parallelize linearly).  The TPU number is the repo's exact O(N²)
jitted t-SNE (`viz/tsne.py`) at the reference's headline 5,000
iterations — exact, not approximate: at N=24k the N² kernels are dense
MXU/VPU work, which is the TPU-first trade.

(b) corpus correlation — the reference's C1 scale story is a Ray
cluster running pandas ``data.corr()`` per study
(``src/generate_gene_pairs.py:49,167-191``).  Measured here per GEO-like
study (5,000 genes x 100 samples) and for a 50-study corpus build:
pandas ``DataFrame.corr`` on this host vs the repo's standardized-matmul
``abs_correlation`` (numpy BLAS and jax/TPU backends,
`corpus/builder.py:113`).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_GENES_TSNE = 24447
DIM = 200
TSNE_ITERS = 5000
STUDY_GENES, STUDY_SAMPLES, N_STUDIES = 5000, 100, 50


def bench_tsne(out: dict) -> None:
    from gene2vec_tpu.viz.tsne import TSNE, TSNEConfig, pca_reduce

    rng = np.random.RandomState(0)
    x = rng.randn(N_GENES_TSNE, DIM).astype(np.float32)

    cfg = TSNEConfig(perplexity=30.0, learning_rate=200.0, n_iter=TSNE_ITERS)
    t = TSNE(config=cfg)
    # _segment is jitted with `steps` static, so only an IDENTICAL run
    # warms the cache — time the second of two full runs (the first pays
    # compile for both the calibration and the 5000-iter segment)
    t.fit(x, snapshot_iters=[TSNE_ITERS], log=lambda m: None)
    t0 = time.perf_counter()
    t.fit(x, snapshot_iters=[TSNE_ITERS], log=lambda m: None)
    tpu_s = time.perf_counter() - t0
    out["tsne"] = {
        "n": N_GENES_TSNE,
        "dim_in": DIM,
        "pca_dims": cfg.pca_dims,
        "iters": TSNE_ITERS,
        "tpu_exact_seconds": round(tpu_s, 1),
        "tpu_iters_per_sec": round(TSNE_ITERS / tpu_s, 1),
    }

    # CPU denominator: sklearn Barnes-Hut at its minimum 250 iterations,
    # same PCA-50 input, then linear projections (both flagged).
    try:
        from sklearn.manifold import TSNE as SkTSNE

        xp = pca_reduce(x, cfg.pca_dims)
        cpu_iters = 250
        sk = SkTSNE(
            n_components=2, perplexity=30, learning_rate=200,
            max_iter=cpu_iters, init="random", method="barnes_hut",
            random_state=0,
        )
        t0 = time.perf_counter()
        sk.fit_transform(xp)
        cpu_s = time.perf_counter() - t0
        per_iter = cpu_s / cpu_iters
        proj_5000_32t = per_iter * TSNE_ITERS / 32.0
        out["tsne"].update({
            "cpu_sklearn_bh_iters": cpu_iters,
            "cpu_sklearn_bh_seconds": round(cpu_s, 1),
            "cpu_5000iter_32thread_seconds_extrapolated": round(
                proj_5000_32t, 1
            ),
            "extrapolated": True,
            "vs_cpu_32thread_equiv": round(proj_5000_32t / tpu_s, 2),
            "note": (
                "CPU rate measured on 1 core at 250 BH iters "
                "(neighbor-build amortized in, favoring CPU per-iter), "
                "scaled linearly to 5000 iters / 32 threads — an upper "
                "bound for BH scaling.  TPU path is EXACT t-SNE "
                "(no BH approximation) at the same perplexity/lr."
            ),
        })
    except Exception as e:  # pragma: no cover - recorded, not hidden
        out["tsne"]["cpu_error"] = repr(e)


def bench_corr(out: dict) -> None:
    import pandas as pd

    from gene2vec_tpu.corpus.builder import abs_correlation

    rng = np.random.RandomState(0)
    study = rng.randn(STUDY_SAMPLES, STUDY_GENES).astype(np.float64)
    df = pd.DataFrame(study)

    t0 = time.perf_counter()
    c_pd = df.corr().to_numpy()
    pandas_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    c_np = abs_correlation(study, backend="numpy")
    numpy_s = time.perf_counter() - t0

    # jax backend: first call compiles; time the steady-state call and
    # a full 50-study serial build
    abs_correlation(study, backend="jax")
    t0 = time.perf_counter()
    c_jx = abs_correlation(study, backend="jax")
    jax_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for s in range(N_STUDIES):
        abs_correlation(study, backend="jax")
    jax_50_s = time.perf_counter() - t0

    err_np = float(np.max(np.abs(np.abs(c_pd) - c_np)))
    err_jx = float(np.max(np.abs(np.abs(c_pd) - c_jx)))
    out["corpus_corr"] = {
        "genes": STUDY_GENES,
        "samples": STUDY_SAMPLES,
        "pandas_corr_seconds_per_study": round(pandas_s, 3),
        "numpy_matmul_seconds_per_study": round(numpy_s, 3),
        "jax_tpu_seconds_per_study": round(jax_s, 3),
        "jax_tpu_seconds_50_studies": round(jax_50_s, 2),
        "pandas_50_studies_seconds_projected": round(pandas_s * N_STUDIES, 1),
        "vs_pandas_per_study": round(pandas_s / jax_s, 1),
        "max_abs_err_numpy_vs_pandas": err_np,
        "max_abs_err_jax_vs_pandas": err_jx,
        "note": (
            "reference scales C1 with a Ray cluster running pandas "
            ".corr() per study; one chip's serial matmul covers the "
            "50-study GEO-like corpus in jax_tpu_seconds_50_studies"
        ),
    }


def main() -> None:
    out: dict = {}
    bench_corr(out)
    print(json.dumps(out.get("corpus_corr", {})), file=sys.stderr, flush=True)
    bench_tsne(out)
    with open(os.path.join(REPO, "AUX_BENCH_r04.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
