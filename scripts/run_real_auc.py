"""Real-data GGIPNN ROC-AUC on the reference's predictionData, under two
protocols, writing ``REAL_AUC.json`` at the repo root.

**Why two protocols.** The reference's train/valid/test splits are
*pairwise gene-disjoint* — zero genes are shared between any two splits
(train 8,832 genes, valid 1,173, test 2,467; all intersections empty —
verified by this script, recorded in the output).  The GGIPNN harness
backfills unseen genes with random U(−0.25, 0.25) rows
(``/root/reference/src/GGIPNN_util.py:6-14``), so an embedding trained on
any in-repo corpus carries *no information* about test-split genes: the
published test AUC ≈ 0.7+ is only reachable with the pretrained GEO
co-expression embedding (24k-gene coverage) that the reference does not
distribute (``.MISSING_LARGE_BLOBS``).  Scoring a train-split-trained
embedding on that test split — round 2's protocol — measures nothing but
chance, whatever the embedding's quality.

1. **reference protocol** (structural control): the reference's exact flow
   (``src/GGIPNN_Classification.py:40-254``) with (a) a random-init frozen
   table and (b) a self-trained frozen embedding.  Both are expected to
   land at AUC ≈ 0.5 on the gene-disjoint test split; they are recorded to
   document the structure, not to measure embedding quality.

2. **holdout protocol** (the quality measurement): hold out 20% of the
   train split's *pairs*, train SGNS on the remaining positives, train the
   GGIPNN on the remaining pairs with the frozen self-trained embedding,
   and score the held-out pairs — seen genes, unseen pairs: standard link
   prediction.  Controls: the same GGIPNN over a random-init frozen table,
   and a classifier-free cosine ranking.  The native sequential CPU oracle
   reaches holdout cosine AUC ≈ 0.88 here; the TPU default config matches
   it (docs/QUALITY_NOTES.md §1, §5).

Usage::

    python scripts/run_real_auc.py [--protocol both|holdout|reference]
        [--emb-iters 50] [--batch-pairs 4096] [--negative-mode shared]
        [--combiner capped] [--shared-pool 0] [--shared-groups 0]
        [--epochs 1] [--data-dir DIR] [--out FILE]
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gene2vec_tpu.eval.holdout import (  # noqa: E402
    HOLDOUT_FRACTION,
    HOLDOUT_SEED,
    load_holdout,
    read_split,
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _round4(x):
    """round() that keeps a missing AUC as JSON null, not literal NaN."""
    return round(x, 4) if x is not None else None


def gene_disjointness(data_dir: str) -> dict:
    """Document the split structure that makes the reference protocol a
    structural control (all pairwise intersections are empty)."""
    genes = {}
    for split in ("train", "valid", "test"):
        lines, _ = read_split(data_dir, split)
        genes[split] = set(g for pair in lines for g in pair)
    return {
        "genes_per_split": {s: len(g) for s, g in genes.items()},
        "shared_train_valid": len(genes["train"] & genes["valid"]),
        "shared_train_test": len(genes["train"] & genes["test"]),
        "shared_valid_test": len(genes["valid"] & genes["test"]),
    }


def sgns_config(args, dim=200):
    from gene2vec_tpu.config import SGNSConfig

    kw = dict(
        dim=dim,
        num_iters=args.emb_iters,
        batch_pairs=args.batch_pairs,
        negative_mode=args.negative_mode,
        combiner=args.combiner,
        shared_groups=args.shared_groups,
    )
    if args.shared_pool > 0:
        kw.update(shared_pool=args.shared_pool, shared_pool_auto=False)
    return SGNSConfig(**kw)


def train_embedding(corpus, out_dir: str, args) -> str:
    """Train SGNS on a positive-pair corpus; return the w2v-format export
    path and record the loss trajectory."""
    from gene2vec_tpu.sgns.train import SGNSTrainer

    cfg = sgns_config(args)
    log(
        f"SGNS: {corpus.num_pairs} positive pairs, vocab {corpus.vocab_size}, "
        f"{cfg.num_iters} iters, B={cfg.batch_pairs}, {cfg.negative_mode}/"
        f"{cfg.combiner}"
    )
    t0 = time.perf_counter()
    SGNSTrainer(corpus, cfg).run(out_dir, log=lambda m: None)
    log(f"SGNS training took {time.perf_counter() - t0:.1f}s")
    w2v = os.path.join(out_dir, f"gene2vec_dim_{cfg.dim}_iter_{cfg.num_iters}_w2v.txt")
    assert os.path.exists(w2v), w2v
    return w2v


def loss_curve(export_dir: str) -> list:
    with open(os.path.join(export_dir, "training_log.csv")) as f:
        return [round(float(row["loss"]), 4) for row in csv.DictReader(f)]


def cosine_auc(w2v_path: str, pairs, labels) -> dict:
    """Classifier-free control: rank pairs by embedding cosine.

    Reported twice: over all pairs (out-of-vocab genes score 0 — genes
    absent from every positive fit pair are themselves a legitimate
    negative signal) and over the harder in-vocab-only subset, where the
    ranking must come entirely from learned geometry.
    """
    from gene2vec_tpu.eval.holdout import cosine_scores
    from gene2vec_tpu.eval.metrics import roc_auc_score
    from gene2vec_tpu.io.emb_io import read_word2vec_format

    toks, mat = read_word2vec_format(w2v_path)
    idx = {t: i for i, t in enumerate(toks)}
    labels = np.asarray(labels)
    scores, in_vocab = cosine_scores(idx, mat, pairs)
    return {
        "all_pairs": round(roc_auc_score(labels, scores), 4),
        "in_vocab_pairs": round(
            roc_auc_score(labels[in_vocab], scores[in_vocab]), 4
        ),
        "in_vocab_count": int(in_vocab.sum()),
    }


def write_splits(dir_, splits) -> None:
    """Write {name: (lines, labels)} in the reference's directory format."""
    for name, (lines, labels) in splits.items():
        with open(os.path.join(dir_, f"{name}_text.txt"), "w") as f:
            f.writelines(" ".join(p) + "\n" for p in lines)
        with open(os.path.join(dir_, f"{name}_label.txt"), "w") as f:
            f.writelines(f"{int(y)}\n" for y in labels)


def run_holdout(args, results: dict) -> None:
    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_train import run_classification

    emb_corpus, split = load_holdout(args.data_dir)
    fit = (split.fit_pairs, split.fit_labels)
    hold = (split.hold_pairs, split.hold_labels)
    pos = split.fit_positives
    # dev slice for GGIPNN training-loop monitoring only: a view of fit
    # (never of holdout); per the canonical protocol it must NOT shrink
    # the embedding corpus or the classifier's training set
    dev_n = min(5000, len(fit[0]) // 10)
    dev = (fit[0][:dev_n], fit[1][:dev_n])
    log(
        f"holdout protocol: fit {len(fit[0])} pairs ({len(pos)} positive), "
        f"dev view {dev_n}, holdout {len(hold[0])}"
    )

    cfg = GGIPNNConfig(num_epochs=args.epochs)
    out = {
        "protocol": {
            "holdout_fraction": HOLDOUT_FRACTION,
            "seed": HOLDOUT_SEED,
            "fit_pairs": len(fit[0]),
            "holdout_pairs": len(hold[0]),
            "emb_corpus": "fit-split positive pairs only",
        }
    }
    with tempfile.TemporaryDirectory() as tmp:
        emb_dir = os.path.join(tmp, "emb")
        os.makedirs(emb_dir)
        w2v = train_embedding(emb_corpus, emb_dir, args)
        curve = loss_curve(emb_dir)
        out["sgns_loss_first"] = curve[0]
        out["sgns_loss_last"] = curve[-1]
        out["sgns_loss_decreasing"] = curve[-1] < curve[0] - 1.0
        log(f"SGNS loss: {curve[0]} -> {curve[-1]}")

        out["cosine_auc"] = cosine_auc(w2v, *hold)
        log(f"holdout cosine AUC (classifier-free): {out['cosine_auc']}")

        split_dir = os.path.join(tmp, "splits")
        os.makedirs(split_dir)
        write_splits(split_dir, {"train": fit, "valid": dev, "test": hold})

        t0 = time.perf_counter()
        log("=== GGIPNN on holdout, frozen self-trained embedding ===")
        res = run_classification(split_dir, emb_path=w2v, config=cfg, log=log)
        out["ggipnn_auc"] = _round4(res.get("auc"))
        out["ggipnn_accuracy"] = round(res["accuracy"], 4)
        out["ggipnn_seconds"] = round(time.perf_counter() - t0, 1)

        log("=== GGIPNN on holdout, random-init control ===")
        res = run_classification(split_dir, emb_path=None, config=cfg, log=log)
        out["ggipnn_auc_random_init"] = _round4(res.get("auc"))
    results["holdout"] = out


def run_reference(args, results: dict) -> None:
    """The reference's own gene-disjoint flow — structural controls."""
    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_train import run_classification

    cfg = GGIPNNConfig(num_epochs=args.epochs)
    out = {}
    t0 = time.perf_counter()
    log("=== reference split, random-init table (quirk #13 path) ===")
    res = run_classification(args.data_dir, emb_path=None, config=cfg, log=log)
    out["random_init"] = {
        "auc": _round4(res.get("auc")),
        "accuracy": round(res["accuracy"], 4),
        "seconds": round(time.perf_counter() - t0, 1),
    }

    log("=== reference split, self-trained frozen embedding ===")
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    lines, labels = read_split(args.data_dir, "train")
    pos = [p for p, y in zip(lines, labels) if y == 1]
    vocab = Vocab.from_pairs(pos)
    corpus = PairCorpus(vocab, vocab.encode_pairs(pos))
    with tempfile.TemporaryDirectory() as tmp:
        w2v = train_embedding(corpus, tmp, args)
        t0 = time.perf_counter()
        res = run_classification(
            args.data_dir, emb_path=w2v, config=cfg, log=log,
            run_dir=args.run_dir,
        )
        out["self_trained"] = {
            "auc": _round4(res.get("auc")),
            "accuracy": round(res["accuracy"], 4),
            "seconds": round(time.perf_counter() - t0, 1),
        }
    out["note"] = (
        "structural control: splits are gene-disjoint, unseen genes get "
        "random rows, so ~0.5 is the expected ceiling for ANY in-repo-"
        "trained embedding (see module docstring)"
    )
    results["reference_split"] = out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="/root/reference/predictionData")
    ap.add_argument(
        "--protocol", choices=("both", "holdout", "reference"), default="both"
    )
    ap.add_argument("--epochs", type=int, default=1,
                    help="GGIPNN epochs (reference default 1)")
    ap.add_argument("--emb-iters", type=int, default=50)
    ap.add_argument("--batch-pairs", type=int, default=4096)
    ap.add_argument("--negative-mode",
                    choices=("stratified", "shared", "per_example"),
                    default="stratified")
    ap.add_argument("--combiner", choices=("capped", "sum", "mean"),
                    default="capped")
    ap.add_argument("--shared-pool", type=int, default=0,
                    help="explicit total pool size (disables auto sizing)")
    ap.add_argument("--shared-groups", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(REPO, "REAL_AUC.json"))
    ap.add_argument("--run-dir", default=None,
                    help="runs/<ts>-style artifact dir for the reference-"
                    "protocol GGIPNN run (step-loop cadence: summaries + "
                    "keep-5 checkpoints — the reference-comparison mode)")
    args = ap.parse_args()

    results = {
        "data": gene_disjointness(args.data_dir),
        "sgns_config": {
            "emb_iters": args.emb_iters,
            "batch_pairs": args.batch_pairs,
            "negative_mode": args.negative_mode,
            "combiner": args.combiner,
            "shared_pool": args.shared_pool or "auto",
            "shared_groups": args.shared_groups or "auto",
        },
    }
    if args.protocol in ("both", "holdout"):
        run_holdout(args, results)
    if args.protocol in ("both", "reference"):
        run_reference(args, results)

    # provenance stamp (the ledger contract, docs/BENCHMARKS.md): the
    # committed REAL_AUC.json must not ingest as legacy_unstamped
    from bench import bench_stamp

    bench_stamp(results)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
