"""Produce the real GGIPNN ROC-AUC on the reference's predictionData splits
(train 263,016 / valid 5,568 / test 21,448 gene pairs — the evaluation the
reference scores at ``src/GGIPNN_Classification.py:246-254``).

Two configurations are recorded (VERDICT round-1, item 2):

1. **random-init embedding** — ``use_pre_trained_gene2vec=False`` path
   (SURVEY §2.2 #13): the table keeps its random-uniform init and trains
   frozen=False... the reference keeps the table *trainable* in that path
   only implicitly; here we mirror the reference default (frozen table,
   embed_train=False) with a random table, the honest lower bound.
2. **self-trained embedding** — an SGNS embedding trained by this
   framework on the positive train-split pairs (label==1), exported in
   word2vec format and loaded frozen, mirroring the published-artifact
   flow.  NOTE: the reference's published embedding was trained on a
   984-dataset GEO co-expression corpus that is not distributed with the
   repo (``.MISSING_LARGE_BLOBS``); the positive-pair corpus is the
   closest in-repo reproducible stand-in.

Writes REAL_AUC.json at the repo root and prints one JSON line.

Usage: python scripts/run_real_auc.py [--data-dir DIR] [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def train_embedding(train_text: str, out_dir: str, num_iters: int) -> str:
    """Train SGNS on the positive train pairs; return w2v-format emb path."""
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.train import SGNSTrainer

    labels_path = train_text.replace("_text", "_label")
    with open(train_text) as f:
        lines = [l.split() for l in f if l.strip()]
    with open(labels_path) as f:
        labels = [int(l) for l in f if l.strip()]
    pos = [l for l, y in zip(lines, labels) if y == 1]
    log(f"positive train pairs: {len(pos)} of {len(lines)}")

    vocab = Vocab.from_pairs(pos)
    corpus = PairCorpus(vocab, vocab.encode_pairs(pos))
    cfg = SGNSConfig(dim=200, num_iters=num_iters, batch_pairs=16384)
    trainer = SGNSTrainer(corpus, cfg)
    t0 = time.perf_counter()
    trainer.run(out_dir, log=log)
    log(f"SGNS training took {time.perf_counter() - t0:.1f}s")
    w2v = os.path.join(out_dir, f"gene2vec_dim_200_iter_{num_iters}_w2v.txt")
    assert os.path.exists(w2v), w2v
    return w2v


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--data-dir", default="/root/reference/predictionData",
        help="directory with {train,valid,test}_{text,label}.txt",
    )
    ap.add_argument("--epochs", type=int, default=1)  # reference default
    ap.add_argument("--emb-iters", type=int, default=10)
    ap.add_argument("--out", default=os.path.join(REPO, "REAL_AUC.json"))
    args = ap.parse_args()

    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_train import run_classification

    results = {}

    cfg = GGIPNNConfig(num_epochs=args.epochs)
    t0 = time.perf_counter()
    log("=== GGIPNN with random-init table (quirk #13 path) ===")
    res = run_classification(args.data_dir, emb_path=None, config=cfg, log=log)
    results["random_init"] = {
        "auc": res.get("auc"), "accuracy": res["accuracy"],
        "seconds": round(time.perf_counter() - t0, 1),
    }

    log("=== training SGNS embedding on positive train pairs ===")
    with tempfile.TemporaryDirectory() as tmp:
        w2v = train_embedding(
            os.path.join(args.data_dir, "train_text.txt"), tmp, args.emb_iters
        )
        t0 = time.perf_counter()
        log("=== GGIPNN with self-trained frozen embedding ===")
        res = run_classification(args.data_dir, emb_path=w2v, config=cfg, log=log)
        results["self_trained_emb"] = {
            "auc": res.get("auc"), "accuracy": res["accuracy"],
            "seconds": round(time.perf_counter() - t0, 1),
        }

    results["config"] = {
        "splits": "reference predictionData (263016/5568/21448)",
        "batch_size": cfg.batch_size,
        "num_epochs": args.epochs,
        "embed_train": cfg.embed_train,
        "emb_corpus": "positive train pairs (GEO corpus not distributed)",
    }
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
