#!/usr/bin/env python
"""Chaos drill: rehearse the failure model against the real CLIs.

Five phases (docs/RESILIENCE.md runbook):

* **training_resume** — run the real training CLI to completion as the
  reference, then SIGKILL a second run at a random ``iteration N done``
  line (mid-checkpoint territory) and rerun it; the resumed run's final
  embedding must be BIT-exact against the uninterrupted one.  A third
  run takes SIGTERM instead and must drain: exit ``EXIT_PREEMPTED``,
  stamp ``interrupted=true`` in its run manifest, and also resume
  bit-exact.
* **corruption** — truncate the newest checkpoint npz / corrupt a
  manifest CRC and assert verified discovery falls back to the previous
  iteration instead of surfacing the torn one.
* **serve** — spawn the real serve CLI over a live export dir: a good
  newer checkpoint hot-swaps in; a TORN newer checkpoint is never
  swapped (the watcher keeps serving the last good iteration); deleting
  the torn files mid-poll doesn't disturb the watcher; a subsequent
  good checkpoint swaps normally.
* **async_overhead** — train at the geometry pinned in
  ``analysis/budgets.json`` (section ``resilience``) with
  ``async_checkpoint`` on and assert the train loop's checkpoint span
  costs less than ``max_overhead_fraction`` of iteration wall time.
* **fleet** — spawn the real ``cli.fleet`` (3 supervised replicas + the
  resilient front-door proxy) over a live export, run closed-loop load
  through a :class:`~gene2vec_tpu.serve.client.ResilientClient` while
  one replica is SIGKILLed mid-run and another serves with injected
  HTTP faults (``resilience/faults.py``: latency, 503 substitution,
  connection resets, blackholes); assert client-observed availability
  >= the ``fleet`` budget, ZERO answers that are wrong or mix model
  iterations, and retry amplification within the retry budget.  Results
  are stamped into ``BENCH_FLEET_r08.json`` via ``--fleet-out`` and
  re-gated on every ``cli.analyze`` run
  (``analysis/passes_fleet.py``).
* **alerts** — the detection loop (docs/OBSERVABILITY.md#alerting):
  spawn ``cli.fleet`` with the default SLO alert rules, prove a CLEAN
  warmup fires nothing, then load a route where one byzantine replica
  injects deterministic 404s + latency and measure how long until the
  availability burn-rate rule fires in ``alerts.jsonl``; the
  auto-assembled incident bundle must CRC-verify via ``cli.obs
  incident`` and contain a reassembled trace through the faulty
  replica.  Stamped into ``BENCH_ALERTS_r13.json`` via ``--alerts-out``
  and gated by ``analysis/passes_alerts.py`` (budgets.json ``alerts``).
* **autoscale** — the elastic fleet (docs/SERVING.md#elastic-fleet):
  spawn ``cli.fleet --max-replicas`` and prove a load ramp produces a
  scale-up DECISION within the budgeted scrape ticks; ramp down and
  prove the hysteresis scale-down drains the victim with ZERO
  dropped/wrong/mixed answers under continuous verified load, plus a
  steady-state window with ZERO further actions (no flapping); then a
  per-tenant-quota fleet must hold a paced victim tenant at >= 0.99
  availability while an abusive tenant floods (tenant-labeled 429s).
  Stamped into ``BENCH_AUTOSCALE_r14.json`` via ``--autoscale-out``
  and gated by ``analysis/passes_autoscale.py`` (budgets.json
  ``autoscale``).
* **shard** — fleet-sharded index serving
  (docs/SERVING.md#sharded-index-serving): an in-process 10M-row
  scatter-merge bench (per-shard IVF+int8 indexes + the cross-process
  ``merge_shard_topk``; recall@10 vs the exact oracle all-up AND with
  one shard removed — the drop must track that shard's row fraction),
  then the real ``cli.fleet --shard-by-rows``: SIGKILL one shard
  mid-load (availability >= 0.99 with ZERO 5xx — dead-shard answers
  are flagged degraded 200s scored against the exact restricted
  oracle; full recall after the supervisor restart), a
  swap-under-load through the shard-atomic stage/flip coordinator
  (ZERO wrong or mixed-iteration answers — the epoch fence), a
  reassembled ``proxy_scatter`` trace, and a slow-loris shard (p99
  bounded by the per-shard deadline, not the fault).  Stamped into
  ``BENCH_SHARD_r15.json`` via ``--shard-out`` and gated by
  ``analysis/passes_shard.py`` (budgets.json ``shard``).
* **loop** — the continuous-learning cycle (docs/CONTINUOUS.md):
  pretrain a serving model, spawn ``cli.fleet --enable-shadow``, keep
  verified light load flowing, then drive a full
  ingest → warm-start train → quality gate → shadow canary → promote
  cycle through ``cli.loop`` with a REAL SIGKILL injected in every
  loop state (resumed from the journal each time).  Assert the fleet
  adopts the promoted iteration (new genes included) with ZERO wrong
  or mixed-iteration answers, the resumed candidate is BIT-exact vs an
  uninterrupted control, and churn / shadow p99 delta / promotion
  decision latency land inside budgets.json ``loop``.  Stamped into
  ``BENCH_LOOP_r16.json`` via ``--loop-out`` and gated by
  ``analysis/passes_loop.py``.
* **batch** — the offline analytics plane (docs/BATCH.md): submit a
  full-vocab kNN graph job to a live SHARDED ``cli.fleet --jobs-dir``
  front door's ``/v1/jobs``, SIGKILL the whole fleet mid-build,
  restart it on the same dirs and let the journaled job resume from
  its committed cursor; the fetched artifact must be BYTE-identical
  to an uninterrupted control built through the same scatter path and
  hit the sampled brute-force oracle recall floor; then prove the
  interactive p99 survives a concurrent build in the background lane
  (``scripts/serve_loadgen.py --batch-phase``) and measure the 1M-row
  IVF scaling table.  Stamped into ``BENCH_BATCH_r19.json`` via
  ``--batch-out`` and gated by ``analysis/passes_batch.py``
  (budgets.json ``batch``).

Exactly ONE JSON document goes to stdout (the machine contract);
progress chatter goes to stderr.  Exit 0 iff every phase passed.

Usage::

    python scripts/chaos_drill.py                 # full drill
    python scripts/chaos_drill.py --smoke         # CI-sized (~2 min)
    python scripts/chaos_drill.py --out BENCH_RESILIENCE_r07.json
    python scripts/chaos_drill.py --only fleet --fleet-out BENCH_FLEET_r08.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gene2vec_tpu.resilience import chaos  # noqa: E402
from gene2vec_tpu.resilience.preempt import EXIT_PREEMPTED  # noqa: E402


def log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def make_corpus(dirpath: str, vocab: int = 30, lines: int = 400,
                seed: int = 7) -> None:
    rng = np.random.RandomState(seed)
    os.makedirs(dirpath, exist_ok=True)
    rows = []
    for _ in range(lines):
        c = rng.randint(3)
        a, b = rng.choice(vocab // 3, 2, replace=False) + (vocab // 3) * c
        rows.append(f"G{a} G{b}")
    with open(os.path.join(dirpath, "pairs.txt"), "w") as f:
        f.write("\n".join(rows) + "\n")


def wait_until(fn, timeout_s: float, interval_s: float = 0.1,
               what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval_s)
    raise TimeoutError(f"{what} not reached within {timeout_s}s")


# -- phase: training resume-equivalence -------------------------------------


def drill_training_resume(tmp: str, iters: int, seed: int) -> dict:
    from gene2vec_tpu.io import checkpoint as ckpt

    data = os.path.join(tmp, "corpus")
    make_corpus(data)
    flags = dict(dim=8, iters=iters, batch_pairs=64, seed=3)

    log("training reference run (uninterrupted)")
    ref_dir = os.path.join(tmp, "train_ref")
    r = chaos.run_cli(chaos.gene2vec_argv(data, ref_dir, **flags))
    assert r.returncode == 0, f"reference run failed:\n{r.output[-2000:]}"
    ref = chaos.load_table(ref_dir, 8, iters)

    kill_at = int(np.random.RandomState(seed).randint(1, iters))
    log(f"SIGKILL run at 'iteration {kill_at} done'")
    kill_dir = os.path.join(tmp, "train_kill")
    r = chaos.run_cli_kill_on(
        chaos.gene2vec_argv(data, kill_dir, **flags),
        rf"iteration {kill_at} done", sig=signal.SIGKILL,
    )
    assert r.returncode != 0, "SIGKILLed child reported success"
    survivor = ckpt.latest_iteration(kill_dir, 8)
    assert survivor <= kill_at, (
        f"latest verified iteration {survivor} > kill point {kill_at}"
    )
    log(f"killed after iteration {kill_at}; verified survivor: {survivor}; "
        "resuming")
    r = chaos.run_cli(chaos.gene2vec_argv(data, kill_dir, **flags))
    assert r.returncode == 0, f"resume failed:\n{r.output[-2000:]}"
    resumed = chaos.load_table(kill_dir, 8, iters)
    kill_exact = bool(np.array_equal(ref, resumed))
    assert kill_exact, "SIGKILL resume diverged from the uninterrupted run"

    log("SIGTERM drain run at 'iteration 1 done'")
    term_dir = os.path.join(tmp, "train_term")
    r = chaos.run_cli_kill_on(
        chaos.gene2vec_argv(data, term_dir, **flags),
        r"iteration 1 done", sig=signal.SIGTERM,
    )
    assert r.returncode == EXIT_PREEMPTED, (
        f"SIGTERM drain exited {r.returncode}, expected {EXIT_PREEMPTED}:\n"
        f"{r.output[-2000:]}"
    )
    with open(os.path.join(term_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest.get("interrupted") is True, "manifest not stamped"
    r = chaos.run_cli(chaos.gene2vec_argv(data, term_dir, **flags))
    assert r.returncode == 0, f"post-drain resume failed:\n{r.output[-2000:]}"
    term_exact = bool(np.array_equal(ref, chaos.load_table(term_dir, 8, iters)))
    assert term_exact, "SIGTERM resume diverged from the uninterrupted run"
    return {
        "iters": iters,
        "sigkill_at_iteration": kill_at,
        "verified_survivor_iteration": survivor,
        "sigkill_resume_bit_exact": kill_exact,
        "sigterm_exit_code": EXIT_PREEMPTED,
        "sigterm_manifest_interrupted": True,
        "sigterm_resume_bit_exact": term_exact,
    }


# -- phase: corruption detection --------------------------------------------


def drill_corruption(tmp: str) -> dict:
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.resilience import snapshot as snap
    from gene2vec_tpu.sgns.model import SGNSParams

    d = os.path.join(tmp, "corrupt")
    vocab = Vocab([f"G{i}" for i in range(16)], np.arange(1, 17))
    for it in (1, 2, 3):
        params = SGNSParams(
            emb=np.full((16, 4), it, np.float32),
            ctx=np.zeros((16, 4), np.float32),
        )
        ckpt.save_iteration(d, 4, it, params, vocab)

    chaos.truncate_file(os.path.join(d, "gene2vec_dim_4_iter_3.npz"))
    snap.clear_verify_cache()
    after_truncate = ckpt.latest_iteration(d, 4)
    assert after_truncate == 2, (
        f"truncated newest not skipped: latest={after_truncate}"
    )

    chaos.corrupt_manifest_crc(os.path.join(d, "gene2vec_dim_4_iter_2"))
    snap.clear_verify_cache()
    after_crc = ckpt.latest_iteration(d, 4)
    assert after_crc == 1, f"stale CRC not skipped: latest={after_crc}"
    log("corruption: truncation and CRC rot both fall back")
    return {
        "truncated_newest_falls_back_to": after_truncate,
        "corrupt_crc_falls_back_to": after_crc,
    }


# -- phase: serve no-garbage-swap -------------------------------------------


def _http_json(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _write_iteration(export_dir: str, it: int, vocab_size: int = 16,
                     dim: int = 4) -> str:
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(it)
    vocab = Vocab([f"G{i}" for i in range(vocab_size)],
                  np.arange(1, vocab_size + 1))
    params = SGNSParams(
        emb=rng.randn(vocab_size, dim).astype(np.float32),
        ctx=np.zeros((vocab_size, dim), np.float32),
    )
    ckpt.save_iteration(export_dir, dim, it, params, vocab)
    return os.path.join(export_dir, f"gene2vec_dim_{dim}_iter_{it}")


def drill_serve(tmp: str) -> dict:
    export_dir = os.path.join(tmp, "serve_export")
    _write_iteration(export_dir, 1)

    # stderr inherits (serve chatter joins the drill's own stderr) so a
    # startup failure is visible, not swallowed into /dev/null
    proc = subprocess.Popen(
        [sys.executable, "-m", "gene2vec_tpu.cli.serve",
         "--export-dir", export_dir, "--port", "0",
         "--poll-interval", "0.3"],
        stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
    )
    try:
        # the contract line is read with a deadline — a serve CLI that
        # hangs before printing it must fail the drill, not wedge it
        # (serve/fleet.py read_contract_line is this exact lesson,
        # extracted; the fleet supervisor and this drill share it)
        from gene2vec_tpu.serve.fleet import read_contract_line

        info = read_contract_line(proc, 120.0)
        url = info["url"]
        log(f"serve CLI up at {url} (iteration {info['iteration']})")

        def iteration() -> int:
            return _http_json(url + "/healthz")["model"]["iteration"]

        assert iteration() == 1

        _write_iteration(export_dir, 2)
        wait_until(lambda: iteration() == 2, 15.0, what="hot swap to iter 2")
        log("good checkpoint hot-swapped")

        # torn newer checkpoint: staged in a side dir, truncated THERE,
        # then moved in (npz first, manifest last) — the watched dir
        # never holds a valid iteration 3 for even a poll cycle, so the
        # only way it can swap in is a verification bug
        stage = os.path.join(tmp, "serve_stage")
        prefix3 = _write_iteration(stage, 3)
        chaos.truncate_file(prefix3 + ".npz")
        base3 = os.path.basename(prefix3)
        for suffix in (".npz", ".txt", "_w2v.txt", ".MANIFEST.json"):
            os.replace(
                prefix3 + suffix, os.path.join(export_dir, base3 + suffix)
            )
        time.sleep(1.5)  # several poll cycles
        assert iteration() == 2, "torn checkpoint was hot-swapped!"
        log("torn checkpoint never swapped in")

        # delete the torn files mid-poll; the watcher must shrug
        chaos.delete_iteration(export_dir, 4, 3)
        time.sleep(0.8)
        assert iteration() == 2

        _write_iteration(export_dir, 4)
        wait_until(lambda: iteration() == 4, 15.0, what="hot swap to iter 4")
        log("recovered with the next good checkpoint")
        health = _http_json(url + "/healthz")
        assert health["status"] == "ok"
        return {
            "hot_swap_good": True,
            "torn_newest_never_swapped": True,
            "delete_mid_poll_survived": True,
            "final_iteration": 4,
        }
    finally:
        proc.kill()
        proc.wait(timeout=30)


# -- phase: fleet survives replica death + injected faults -------------------


def _parse_prom_counters(text: str) -> dict:
    """name -> value for the plain counter/gauge lines of a Prometheus
    text exposition (enough to read the fleet client's retry tallies)."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def _parse_fleet_view(url: str) -> dict:
    """``/metrics/fleet`` → {(name, labels): value} via the escape-aware
    parser the aggregator itself uses."""
    from gene2vec_tpu.obs.aggregate import parse_prometheus

    text = (
        urllib.request.urlopen(url + "/metrics/fleet", timeout=10.0)
        .read().decode("utf-8")
    )
    return {(s.name, s.labels): s.value for s in parse_prometheus(text)}


def _trace_tree_facts(doc: dict) -> "tuple":
    """(node name set, client_attempt count) over the reassembled tree
    including process-local compute subtrees."""
    names = set()
    attempts = 0

    def walk(node: dict) -> None:
        nonlocal attempts
        if node.get("name"):
            names.add(node["name"])
            if node["name"] == "client_attempt":
                attempts += 1
        for sub in node.get("process_spans", []):
            walk(sub)
        for child in node.get("children", []):
            walk(child)

    for root in doc.get("roots", []):
        walk(root)
    return names, attempts


def _find_cross_process_trace(export_dir: str, candidates) -> "tuple":
    """First candidate trace id whose reassembled tree spans the whole
    pipeline (proxy → ≥2 client attempts, i.e. a retried/failed-over
    request → replica → batcher → engine)."""
    from gene2vec_tpu.obs import flight as flight_mod

    for tid in candidates:
        doc = flight_mod.collect_trace(export_dir, tid)
        names, n_attempts = _trace_tree_facts(doc)
        if (
            {"proxy_request", "serve_request", "batch_item",
             "engine_topk"} <= names
            and n_attempts >= 2
        ):
            return tid, names, n_attempts
    return None, set(), 0


def drill_fleet(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    import threading

    from gene2vec_tpu.resilience.faults import FaultSpec
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "fleet_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    replicas = int(budget.get("replicas", 3))
    duration_s = 8.0 if smoke else 20.0
    workers = 4
    # the faulty replica: enough injected trouble to matter, spread over
    # every fault class the injector has; deterministic per drill seed
    faults = FaultSpec(
        seed=seed,
        latency_p=0.25, latency_ms=80.0,
        error_p=0.15, error_status=503,
        reset_p=0.05,
        blackhole_p=0.03, blackhole_hold_s=1.5,
    )
    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", str(replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3", "--proxy-timeout-ms", "4000",
        "--scrape-interval", "0.5",
        "--seed", str(seed),
        # no LRU on the replicas: the drill's 8-gene keyspace would be
        # fully cached after warmup, and a cached answer never touches
        # the batcher/engine — the cross-process trace this phase must
        # reassemble (and the availability gate should cover the whole
        # pipeline, not the cache)
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--replica-arg", "1:--faults", "--replica-arg",
        f"1:{faults.to_json()}",
    ]
    log(f"spawning fleet: {replicas} replicas, faults on replica 1")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        log(f"fleet front door at {url}; replica pids "
            f"{info['replica_pids']}")

        # every drill request is a SAMPLED trace root: the proxy and
        # replicas honor the propagated context, so cross-process
        # reassembly below has the full span pipeline to work with
        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=3, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )
        # pre-chaos reference answers: every response during chaos must
        # match one of these EXACTLY (same neighbors, same iteration) —
        # "zero wrong or cross-iteration answers" is checked per request
        query_genes = [f"G{i}" for i in range(8)]
        reference = {}
        for g in query_genes:
            r = client.request(
                "/v1/similar", {"genes": [g], "k": 4}, timeout_s=10.0
            )
            assert r.ok, f"reference query failed: {r.error_class}"
            reference[g] = (
                r.doc["model"]["iteration"],
                tuple(n["gene"] for n in r.doc["results"][0]["neighbors"]),
            )

        # fleet-view snapshot BEFORE the load window: the availability/
        # rejection numbers /metrics/fleet reports during chaos must be
        # reconcilable with the drill's own counts by delta math
        def _settled_view() -> dict:
            last = None
            for _ in range(30):
                view = _parse_fleet_view(url)
                key = (view.get(("fleet_responses", ())),
                       view.get(("fleet_requests", ())))
                if last is not None and key == last:
                    return view
                last = key
                time.sleep(0.6)
            return view

        pre_view = _settled_view()

        counts = {"ok": 0, "failed": 0, "wrong": 0, "mixed": 0,
                  "attempts": 0, "retries": 0, "rejected": 0}
        ok_latencies = []
        trace_log = []  # (monotonic, trace_id, retries, ok)
        lock = threading.Lock()
        stop_at = time.monotonic() + duration_s

        def worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                r = client.request(
                    "/v1/similar", {"genes": [g], "k": 4}, timeout_s=6.0
                )
                with lock:
                    counts["attempts"] += r.attempts
                    counts["retries"] += r.retries
                    trace_log.append(
                        (time.monotonic(), r.trace_id, r.retries, r.ok)
                    )
                    if r.error_class == "http_429":
                        counts["rejected"] += 1
                    if not r.ok:
                        counts["failed"] += 1
                        continue
                    ok_latencies.append(r.latency_s)
                    it = r.doc["model"]["iteration"]
                    got = tuple(
                        n["gene"]
                        for n in r.doc["results"][0]["neighbors"]
                    )
                    ref_it, ref_neighbors = reference[g]
                    if it != ref_it:
                        counts["mixed"] += 1
                    elif got != ref_neighbors:
                        counts["wrong"] += 1
                    else:
                        counts["ok"] += 1

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()

        # one third in: SIGKILL a healthy replica (index 0; index 1 is
        # the fault-injected one and stays up, misbehaving)
        time.sleep(duration_s / 3.0)
        victim = info["replica_pids"][0]
        log(f"SIGKILL replica 0 (pid {victim}) mid-load")
        os.kill(victim, signal.SIGKILL)
        t_kill = time.monotonic()

        for t in threads:
            t.join(timeout=duration_s + 30.0)

        total = (counts["ok"] + counts["failed"] + counts["wrong"]
                 + counts["mixed"])
        availability = counts["ok"] / max(total, 1)
        # replica-level attempts = front-door requests + its internal
        # retries/hedges (from the fleet /metrics registry); drill-level
        # attempts already count our own client's retry fan-out
        prom = _parse_prom_counters(
            urllib.request.urlopen(url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        proxy_retries = prom.get("fleet_client_retries_total", 0.0)
        proxy_hedges = prom.get("fleet_client_hedges_total", 0.0)
        amplification = (
            (counts["attempts"] + proxy_retries + proxy_hedges)
            / max(total, 1)
        )

        # --- the fleet SLO plane must agree with what we measured ----
        # traffic has stopped, so the aggregator's counters converge;
        # compare by DELTA across the load window.  Per-exchange vs
        # per-logical-request bookkeeping: the proxy counts one
        # response per drill-client ATTEMPT, and only a terminal
        # attempt can be 2xx, so ok≈(ok+wrong+mixed), total≈attempts.
        post_view = _settled_view()

        def _delta(name: str) -> float:
            return (post_view.get((name, ()), 0.0)
                    - pre_view.get((name, ()), 0.0))

        resp_delta = _delta("fleet_responses")
        ok_delta = _delta("fleet_ok")
        fleet_availability = ok_delta / max(resp_delta, 1.0)
        measured_attempt_av = (
            (counts["ok"] + counts["wrong"] + counts["mixed"])
            / max(counts["attempts"], 1)
        )
        fleet_rejection_rate = post_view.get(
            ("fleet_rejection_rate", ()), 0.0
        )
        measured_rejection_rate = counts["rejected"] / max(total, 1)
        fleet_queue_depth = post_view.get(("fleet_queue_depth", ()))
        route_labels = (("route", "/v1/similar"),)
        fleet_p50 = post_view.get(
            ("fleet_route_p50_seconds", route_labels)
        )
        fleet_p99 = post_view.get(
            ("fleet_route_p99_seconds", route_labels)
        )
        ok_latencies.sort()
        drill_p99 = (
            ok_latencies[min(len(ok_latencies) - 1,
                             int(0.99 * len(ok_latencies)))]
            if ok_latencies else None
        )
        log(
            f"fleet view: availability {fleet_availability:.4f} "
            f"(drill attempt-level {measured_attempt_av:.4f}), "
            f"/v1/similar p50/p99 {fleet_p50}/{fleet_p99}s "
            f"(drill client p99 {drill_p99}), queue depth "
            f"{fleet_queue_depth}, rejection {fleet_rejection_rate:.4f}"
        )
        assert resp_delta > 0, "/metrics/fleet saw none of the load"
        assert abs(fleet_availability - measured_attempt_av) <= 0.05, (
            f"/metrics/fleet availability {fleet_availability:.4f} "
            f"disagrees with the drill's measured "
            f"{measured_attempt_av:.4f}"
        )
        assert abs(
            fleet_rejection_rate - measured_rejection_rate
        ) <= 0.05, (
            f"/metrics/fleet rejection rate {fleet_rejection_rate:.4f} "
            f"disagrees with measured {measured_rejection_rate:.4f}"
        )
        assert fleet_queue_depth is not None and fleet_queue_depth >= 0, (
            "fleet_queue_depth missing from /metrics/fleet"
        )
        assert fleet_p50 is not None and fleet_p99 is not None, (
            "per-route p50/p99 missing from /metrics/fleet"
        )
        # replica-side handle time must sit below the client-observed
        # tail (which adds proxy+retries); bucket edges round UP <= 2x
        assert drill_p99 is None or fleet_p99 <= max(
            4.0 * drill_p99, 1.0
        ), (
            f"fleet p99 {fleet_p99}s implausible vs drill-observed "
            f"{drill_p99}s"
        )

        # --- cross-process trace reassembly for a SIGKILL-affected
        # request: an ok answer shortly after the kill whose tree shows
        # the proxy failing over (>= 2 client attempts) down to the
        # engine.  Reassembled in-process to pick a candidate, then
        # re-rendered through the real CLI (the operator's tool).
        time.sleep(1.0)  # let the last events.jsonl appends land
        candidates = [
            tid for (ts, tid, _retries, ok) in trace_log
            if ok and tid and ts >= t_kill
        ][:40]
        trace_id, names, n_attempts = _find_cross_process_trace(
            export_dir, candidates
        )
        assert trace_id is not None, (
            f"no post-SIGKILL request reassembled into a full "
            f"proxy→attempts→replica→batcher→engine trace "
            f"({len(candidates)} candidates tried)"
        )
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "trace",
             export_dir, trace_id],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0, (
            f"cli.obs trace failed (rc={cli.returncode}):\n{cli.stderr}"
        )
        for needle in ("proxy_request", "client_attempt",
                       "serve_request", "batch_item", "engine_topk"):
            assert needle in cli.stdout, (
                f"cli.obs trace output missing {needle!r}:\n{cli.stdout}"
            )
        log(
            f"trace {trace_id} reassembled end-to-end via cli.obs "
            f"trace ({n_attempts} client attempts, hops: "
            f"{sorted(names)})"
        )
        # the respawn is a fresh jax import — under the load the drill
        # itself just generated it can outlast the measurement window,
        # so WAIT for supervision to land rather than asserting on a
        # race (the availability numbers above are already final)
        def _restarts() -> int:
            health = _http_json(url + "/healthz", timeout=10.0)
            return sum(r["restarts"] for r in health["replicas"])

        try:
            restarts = wait_until(
                lambda: _restarts() or None, 90.0, interval_s=0.5,
                what="supervisor restarting the SIGKILLed replica",
            )
        except TimeoutError:
            restarts = 0
        result = {
            "replicas": replicas,
            "duration_s": duration_s,
            "workers": workers,
            "requests": total,
            "ok": counts["ok"],
            "failed": counts["failed"],
            "wrong_answers": counts["wrong"],
            "mixed_iteration_answers": counts["mixed"],
            "availability": round(availability, 5),
            "drill_client_retries": counts["retries"],
            "proxy_retries": int(proxy_retries),
            "retry_amplification": round(amplification, 4),
            "replica_restarts": restarts,
            "fleet_view_availability": round(fleet_availability, 5),
            "fleet_view_matches_measured": True,
            "fleet_route_p50_s": fleet_p50,
            "fleet_route_p99_s": fleet_p99,
            "fleet_queue_depth": fleet_queue_depth,
            "fleet_rejection_rate": round(fleet_rejection_rate, 5),
            "reassembled_trace_id": trace_id,
            "reassembled_trace_client_attempts": n_attempts,
            "faults_spec": faults.to_json(),
            "sigkilled_replica": 0,
            "budget": {k: v for k, v in budget.items()
                       if not k.startswith("_")},
        }
        log(f"fleet: availability {availability:.4f} over {total} "
            f"requests ({counts['failed']} failed), amplification "
            f"{amplification:.3f}, {restarts} restart(s)")
        assert total >= workers * duration_s, (
            f"suspiciously few requests completed ({total}) — the load "
            "loop itself wedged"
        )
        assert counts["mixed"] == 0, (
            f"{counts['mixed']} answers mixed model iterations"
        )
        assert counts["wrong"] == 0, (
            f"{counts['wrong']} answers diverged from the pre-chaos "
            "reference"
        )
        assert availability >= float(budget["min_availability"]), (
            f"availability {availability:.4f} below budget "
            f"{budget['min_availability']}"
        )
        assert amplification <= float(budget["max_retry_amplification"]), (
            f"retry amplification {amplification:.3f} exceeds budget "
            f"{budget['max_retry_amplification']}"
        )
        assert restarts >= 1, (
            "the SIGKILLed replica was never restarted — supervision "
            "is not working"
        )
        return result
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


# -- phase: alert detection + incident capture -------------------------------


def _read_alert_transitions(run_dir: str) -> list:
    from gene2vec_tpu.obs.alerts import collect_transitions

    return collect_transitions(run_dir)


def _trace_doc_pids(doc: dict) -> set:
    """Every pid a reassembled trace document touches (hop nodes,
    process-local subtrees, flight records)."""
    pids = set(doc.get("processes") or [])

    def walk(node: dict) -> None:
        if node.get("pid"):
            pids.add(node["pid"])
        for sub in node.get("process_spans", []):
            walk(sub)
        for child in node.get("children", []):
            walk(child)

    for root in doc.get("roots", []):
        walk(root)
    for rec in doc.get("flight", []):
        if rec.get("pid"):
            pids.add(rec["pid"])
    return pids


def drill_alerts(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """Measure the detection loop end to end: clean warmup fires NOTHING,
    an injected replica fault fires the availability burn-rate rule
    within the budgeted latency, and the auto-assembled incident bundle
    is CRC-verified and holds a reassembled trace through the faulty
    replica."""
    import glob
    import threading

    from gene2vec_tpu.resilience.faults import FaultSpec
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "alerts_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    replicas = int(budget.get("replicas", 3))
    scrape_s = float(budget.get("scrape_interval_s", 0.25))
    proxy_attempts = int(budget.get("proxy_attempts", 1))
    max_latency = float(budget.get("max_detection_latency_s", 20.0))
    warmup_s = 6.0
    workers = 4
    expected_rule = "availability-burn"

    # The faulty replica is BYZANTINE, not crashed: it answers promptly
    # with 404s for valid requests (a bad deploy / corrupted routing
    # table) plus injected latency, scoped to /v1/similar so the warmup
    # route stays clean.  The fault class is chosen deliberately —
    # retry-safe faults (503s, resets, kills) are ABSORBED by the PR-5
    # resilience layer (per-replica breakers eject a 500-spewing
    # replica within seconds; measured here: 8 of 3285 responses
    # surfaced before the breaker closed the tap), so the front door
    # never shows an SLO burn and nothing SHOULD alert.  A 4xx from a
    # replica is classified replica-healthy (never retried, breaker
    # records success) and forwards straight to the caller: a steady,
    # unabsorbable availability burn — exactly the gray-failure class
    # burn-rate alerting exists to catch.
    faults = FaultSpec(
        seed=seed, route_prefix="/v1/similar",
        latency_p=0.5, latency_ms=180.0,
        error_p=0.5, error_status=404,
    )
    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", str(replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3",
        "--proxy-attempts", str(proxy_attempts),
        "--proxy-timeout-ms", "4000",
        "--scrape-interval", str(scrape_s),
        "--alert-rules", "default",
        "--seed", str(seed),
        # no LRU: a cached answer never touches the batcher/engine, and
        # the bundle's reassembled trace must span the whole pipeline
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--replica-arg", "1:--faults", "--replica-arg",
        f"1:{faults.to_json()}",
    ]
    log(f"spawning fleet: {replicas} replicas, byzantine 404s+latency "
        f"on replica 1 (route-scoped to /v1/similar), default alert "
        f"rules")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        run_dir = info["run_dir"]
        faulty_pid = info["replica_pids"][1]
        log(f"fleet front door at {url}; faulty replica pid {faulty_pid}; "
            f"run dir {run_dir}")

        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=1, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )
        query_genes = [f"G{i}" for i in range(8)]
        # prime the /v1/similar compile caches DIRECTLY on every
        # replica, bypassing the proxy: the first top-k batch
        # jit-compiles (~hundreds of ms), and neither the clean-warmup
        # check nor the detection clock may be polluted by it — direct
        # requests never touch the proxy's availability counters.  The
        # faulty replica can 404 a priming request; retry until one
        # compile-carrying 200 lands.
        body = json.dumps(
            {"genes": [query_genes[0]], "k": 4}
        ).encode("utf-8")
        for replica_url in info["replica_urls"]:
            for _ in range(12):
                req = urllib.request.Request(
                    f"{replica_url}/v1/similar", data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    with urllib.request.urlopen(req, timeout=15.0) as r:
                        if r.status == 200:
                            break
                except urllib.error.HTTPError:
                    continue  # injected 404: try again
        # --- clean warmup: load a route the fault spec never matches;
        # ZERO rules may fire.  Lightly paced — the warmup must
        # exercise the pipeline, not flood the burn-rate windows with
        # so much clean traffic that the later fault burn is diluted
        # below its threshold for most of the detection budget.
        log(f"clean warmup ({warmup_s:.0f}s on /v1/embedding)")
        stop_at = time.monotonic() + warmup_s

        def warm_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                client.request("/v1/embedding", {"genes": [g]},
                               timeout_s=6.0)
                time.sleep(0.02)

        threads = [
            threading.Thread(target=warm_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=warmup_s + 30.0)
        time.sleep(max(3 * scrape_s, 1.0))  # let the evaluator tick
        warmup_firings = [
            r for r in _read_alert_transitions(run_dir)
            if r.get("to") == "firing"
        ]
        assert not warmup_firings, (
            f"rule(s) fired during the CLEAN warmup: "
            f"{[r['rule'] for r in warmup_firings]}"
        )
        log("clean warmup: zero rules fired")

        # --- fault exposure: load the faulty route, clock the firing
        t_fault = time.time()
        load_stop = [time.monotonic() + max_latency + 15.0]

        def fault_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 100 + widx)
            while time.monotonic() < load_stop[0]:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                client.request(
                    "/v1/similar", {"genes": [g], "k": 4}, timeout_s=6.0
                )

        threads = [
            threading.Thread(target=fault_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()

        def find_firing():
            for r in _read_alert_transitions(run_dir):
                if (
                    r.get("to") == "firing"
                    and r.get("rule") == expected_rule
                    and r.get("wall", 0.0) >= t_fault
                ):
                    return r
            return None

        firing = wait_until(
            find_firing, max_latency + 5.0, interval_s=0.2,
            what=f"rule {expected_rule!r} firing",
        )
        detection_latency = firing["wall"] - t_fault
        log(f"rule {expected_rule!r} fired {detection_latency:.2f}s after "
            f"the first faulty request (budget {max_latency:g}s)")
        # keep load flowing briefly so the bundle's flight rings and
        # trace window are rich, then stop
        time.sleep(2.0)
        load_stop[0] = 0.0
        for t in threads:
            t.join(timeout=30.0)

        # --- the incident bundle: assembled in the proxy process on its
        # own thread; its manifest is written LAST, so waiting for the
        # manifest waits for the whole bundle
        def find_bundle():
            manifests = glob.glob(os.path.join(
                run_dir, "incidents", "*", "incident.MANIFEST.json"
            ))
            # the availability firing's bundle specifically — another
            # rule may legitimately fire later in the fault window
            mine = [
                os.path.dirname(m) for m in manifests
                if os.path.basename(os.path.dirname(m)).split("_", 1)[-1]
                .startswith(expected_rule)
            ]
            return sorted(mine) or None

        bundles = wait_until(find_bundle, 45.0, interval_s=0.5,
                             what="incident bundle manifest")
        bundle = bundles[0]
        # verify through the operator's tool (cli.obs incident: CRC
        # verification + render; exit 0 is the verified contract)
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "incident",
             bundle],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0, (
            f"cli.obs incident failed (rc={cli.returncode}):\n"
            f"{cli.stdout}\n{cli.stderr}"
        )
        assert "VERIFIED" in cli.stdout, cli.stdout
        # ... and the timeline renderer sees the firing
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "alerts",
             run_dir],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0 and expected_rule in cli.stdout, (
            f"cli.obs alerts missing the firing (rc={cli.returncode}):\n"
            f"{cli.stdout}"
        )

        trace_files = sorted(glob.glob(os.path.join(bundle, "trace-*.json")))
        assert trace_files, "incident bundle reassembled no traces"
        trace_pids = {}
        for path in trace_files:
            with open(path) as f:
                trace_pids[os.path.basename(path)] = _trace_doc_pids(
                    json.load(f)
                )
        through_faulty = [
            name for name, pids in trace_pids.items() if faulty_pid in pids
        ]
        assert through_faulty, (
            f"no bundle trace passes through the faulty replica pid "
            f"{faulty_pid}: {trace_pids}"
        )
        dump_files = sorted(
            os.path.basename(p) for p in
            glob.glob(os.path.join(bundle, "flightdump-*.json"))
        )
        # proxy ring + one dump per live replica — a silently failed
        # /debug/flight fetch (the faulty replica's ring is the
        # interesting one) must fail the drill, not just shrink the
        # bundle
        assert len(dump_files) >= replicas + 1, (
            f"expected flight dumps from the proxy + every live replica "
            f"({replicas + 1}), got {dump_files}"
        )
        assert os.path.exists(
            os.path.join(bundle, "metrics_window.json")
        ), "bundle is missing its raw metrics window"

        all_firings = sorted({
            r["rule"] for r in _read_alert_transitions(run_dir)
            if r.get("to") == "firing"
        })
        result = {
            "replicas": replicas,
            "scrape_interval_s": scrape_s,
            "proxy_attempts": proxy_attempts,
            "warmup_s": warmup_s,
            "workers": workers,
            "expected_rule": expected_rule,
            "fired_rules": all_firings,
            "detection_latency_s": round(detection_latency, 3),
            "warmup_false_positives": len(warmup_firings),
            "bundle": os.path.relpath(bundle, tmp),
            "bundle_verified": True,
            "bundle_traces": len(trace_files),
            "bundle_trace_through_faulty_replica": True,
            "bundle_flight_dumps": len(dump_files),
            "faulty_replica_pid": faulty_pid,
            "faults_spec": faults.to_json(),
            "budget": {k: v for k, v in budget.items()
                       if not k.startswith("_")},
        }
        log(f"alerts: detection {detection_latency:.2f}s, fired "
            f"{all_firings}, bundle {os.path.basename(bundle)} verified "
            f"({len(trace_files)} trace(s), {len(dump_files)} dump(s))")
        assert detection_latency <= max_latency, (
            f"detection latency {detection_latency:.2f}s exceeds budget "
            f"{max_latency:g}s"
        )
        return result
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


# -- phase: elastic autoscaling + tenant isolation ---------------------------


def _parse_labeled_counters(text: str) -> dict:
    """(name, labels) -> value via the aggregator's escape-aware
    parser (labeled tenant series need real label parsing)."""
    from gene2vec_tpu.obs.aggregate import parse_prometheus

    return {(s.name, s.labels): s.value for s in parse_prometheus(text)}


def _fetch_metrics(url: str) -> dict:
    return _parse_prom_counters(
        urllib.request.urlopen(url + "/metrics", timeout=10.0)
        .read().decode("utf-8")
    )


def _replica_states(url: str) -> list:
    return _http_json(url + "/healthz", timeout=10.0)["replicas"]


def drill_autoscale(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """Exercise the elastic fleet end to end: (A) a load ramp must
    produce a scale-up DECISION within the budgeted number of scrape
    ticks; (B) ramp-down must scale back down through the zero-drop
    drain — continuous verified light load sees ZERO dropped, wrong, or
    mixed-iteration answers, and a steady-state window after
    convergence records ZERO further scale actions; (C) an abusive
    tenant flooding far over its token bucket must leave a paced victim
    tenant's availability >= the budget floor, with the abuser's 429s
    landing in the tenant-labeled rejection series."""
    import threading

    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "autoscale_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    min_replicas = int(budget.get("min_replicas", 1))
    max_replicas = int(budget.get("max_replicas", 2))
    scrape_s = float(budget.get("scrape_interval_s", 0.25))
    max_ticks = float(budget.get("max_scale_up_detection_ticks", 40))
    steady_ticks = 16 if smoke else 24
    ramp_workers = 48
    query_genes = [f"G{i}" for i in range(8)]

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir,
        "--replicas", str(min_replicas),
        "--min-replicas", str(min_replicas),
        "--max-replicas", str(max_replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3", "--proxy-timeout-ms", "4000",
        "--proxy-workers", "64",
        "--scrape-interval", str(scrape_s),
        "--alert-rules", "none",
        "--seed", str(seed),
        # the scaler's drill knobs: breach fast (2 ticks), clear slow
        # (12 ticks), short cooldown so the smoke finishes, bounded
        # drain
        "--scale-up-queue", "4", "--scale-up-rejection", "0.02",
        "--scale-up-after", "2", "--scale-down-after", "12",
        "--scale-down-queue", "3", "--scale-cooldown", "1.0",
        "--drain-timeout", "15",
        # replica geometry that makes one replica saturable by a CPU
        # drill (the production knee is ~1,200 rps/replica,
        # BENCH_SERVE_r11; here batches of 4 per 100 ms window cap
        # service at ~40 rps, so 48 closed-loop workers keep the
        # 8-deep queue pinned full and shedding): no LRU (cached
        # answers bypass the queue the ramp must fill), long admission
        # window, tiny batch, small bounded queue, enough HTTP workers
        # that admission — not the handler pool — is the choke point
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--serve-arg=--max-delay-ms", "--serve-arg=100",
        "--serve-arg=--max-batch", "--serve-arg=4",
        "--serve-arg=--max-queue", "--serve-arg=8",
        "--serve-arg=--http-workers", "--serve-arg=32",
    ]
    log(f"spawning elastic fleet: {min_replicas} -> {max_replicas} "
        f"replicas, scrape {scrape_s}s")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        assert info.get("autoscale") == {
            "min": min_replicas, "max": max_replicas
        }, f"contract line missing autoscale facts: {info}"
        log(f"elastic fleet front door at {url}")

        def post(gene: str, timeout: float = 10.0,
                 tenant: str = None) -> "tuple":
            """(status, doc-or-None) for one POST /v1/similar."""
            body = json.dumps({"genes": [gene], "k": 4}).encode("utf-8")
            headers = {"Content-Type": "application/json"}
            if tenant:
                headers["X-Tenant"] = tenant
            req = urllib.request.Request(
                url + "/v1/similar", data=body, headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return r.status, json.loads(
                        r.read().decode("utf-8")
                    )
            except urllib.error.HTTPError as e:
                e.read()
                e.close()
                return e.code, None
            except Exception:
                return 0, None

        # pre-ramp reference answers: everything verified during the
        # scale-down window must match these exactly
        reference = {}
        for g in query_genes:
            status, doc = post(g, timeout=15.0)
            assert status == 200, f"reference query failed ({status})"
            reference[g] = (
                doc["model"]["iteration"],
                tuple(n["gene"] for n in doc["results"][0]["neighbors"]),
            )

        base = _fetch_metrics(url)
        assert base.get("fleet_scale_up_total") == 0.0, (
            "scaler acted before the ramp — thresholds are too twitchy"
        )

        # --- (A) the ramp: saturate the single replica's queue --------
        ramp_stop = threading.Event()
        ramp_counts = {"n": 0, "rejected": 0}
        ramp_lock = threading.Lock()

        def ramp_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while not ramp_stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, _ = post(g, timeout=10.0)
                with ramp_lock:
                    ramp_counts["n"] += 1
                    if status == 429:
                        ramp_counts["rejected"] += 1

        t_ramp = time.monotonic()
        ramp_threads = [
            threading.Thread(target=ramp_worker, args=(w,), daemon=True)
            for w in range(ramp_workers)
        ]
        for t in ramp_threads:
            t.start()

        def scale_up_decided():
            m = _fetch_metrics(url)
            return m.get("fleet_scale_up_total", 0.0) >= 1.0 or None

        wait_until(
            scale_up_decided, max_ticks * scrape_s + 10.0,
            interval_s=0.1, what="scale-up decision",
        )
        detection_s = time.monotonic() - t_ramp
        detection_ticks = max(1, int(np.ceil(detection_s / scrape_s)))
        log(f"scale-up decided {detection_s:.2f}s after the ramp "
            f"({detection_ticks} tick(s) at {scrape_s}s; budget "
            f"{max_ticks:g})")

        # completion is bounded separately: a replica spawn is a full
        # jax import on this host
        def scaled_up():
            ups = [
                r for r in _replica_states(url) if r["state"] == "up"
            ]
            return (len(ups) >= max_replicas) or None

        wait_until(scaled_up, 180.0, interval_s=0.5,
                   what="new replica in rotation")
        scale_up_completed_s = time.monotonic() - t_ramp
        log(f"fleet at {max_replicas} replicas "
            f"{scale_up_completed_s:.1f}s after the ramp started")
        ramp_stop.set()
        for t in ramp_threads:
            t.join(timeout=30.0)

        # --- (B) ramp-down under continuous verified light load -------
        light_stop = threading.Event()
        light = {"n": 0, "dropped": 0, "wrong": 0, "mixed": 0}
        light_lock = threading.Lock()

        def light_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 500 + widx)
            while not light_stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, doc = post(g, timeout=10.0)
                with light_lock:
                    light["n"] += 1
                    if status != 200 or doc is None:
                        # ANY non-200 during scale-down is a drop: the
                        # light load sits far under every threshold, so
                        # the only thing that could fail it is a replica
                        # dying with requests on board
                        light["dropped"] += 1
                        continue
                    ref_it, ref_neighbors = reference[g]
                    it = doc["model"]["iteration"]
                    got = tuple(
                        n["gene"]
                        for n in doc["results"][0]["neighbors"]
                    )
                    if it != ref_it:
                        light["mixed"] += 1
                    elif got != ref_neighbors:
                        light["wrong"] += 1
                time.sleep(0.1)

        light_threads = [
            threading.Thread(target=light_worker, args=(w,), daemon=True)
            for w in range(2)
        ]
        t_down0 = time.monotonic()
        for t in light_threads:
            t.start()

        def scaled_down():
            m = _fetch_metrics(url)
            if m.get("fleet_scale_down_total", 0.0) < 1.0:
                return None
            states = _replica_states(url)
            ups = [r for r in states if r["state"] == "up"]
            return (
                len(states) == min_replicas
                and len(ups) == min_replicas
            ) or None

        # clear window (12 ticks) + drain + cooldown + margin
        wait_until(scaled_down, 12 * scrape_s + 60.0, interval_s=0.5,
                   what="zero-drop scale-down back to min_replicas")
        scale_down_s = time.monotonic() - t_down0
        log(f"scaled back down to {min_replicas} replica(s) in "
            f"{scale_down_s:.1f}s under verified light load")

        # --- steady state: ZERO further actions after convergence -----
        steady_base = _fetch_metrics(url)
        time.sleep(steady_ticks * scrape_s)
        steady_now = _fetch_metrics(url)
        steady_actions = int(
            (steady_now.get("fleet_scale_up_total", 0.0)
             - steady_base.get("fleet_scale_up_total", 0.0))
            + (steady_now.get("fleet_scale_down_total", 0.0)
               - steady_base.get("fleet_scale_down_total", 0.0))
        )
        light_stop.set()
        for t in light_threads:
            t.join(timeout=30.0)
        drain_timeouts = int(
            steady_now.get("fleet_drain_timeouts_total", 0.0)
        )
        log(f"steady state: {steady_actions} scale action(s) over "
            f"{steady_ticks} ticks; light load {light['n']} requests, "
            f"{light['dropped']} dropped, {light['wrong']} wrong, "
            f"{light['mixed']} mixed; drain timeouts {drain_timeouts}")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    # --- (C) tenant isolation: a fresh single-replica fleet with
    # per-tenant token buckets; the abuser floods, the victim paces ----
    victim, abuser = "alice", "mallory"
    tenant_argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", "1",
        "--port", "0", "--health-interval", "0.25",
        "--proxy-timeout-ms", "4000", "--proxy-workers", "64",
        "--scrape-interval", "0.5", "--alert-rules", "none",
        "--seed", str(seed),
        "--serve-arg=--cache-size", "--serve-arg=0",
        # default quota 50 rps (burst 100) for every tenant incl. the
        # abuser; the victim gets an explicit override with a 4x
        # fair-dequeue weight — the drill exercises the override path
        "--serve-arg=--tenant-quota", "--serve-arg=50",
        "--serve-arg=--tenant-override",
        f"--serve-arg={victim}:50:100:4",
    ]
    log("spawning tenant-isolation fleet (1 replica, 50 rps/tenant "
        "token buckets)")
    tduration_s = 6.0 if smoke else 12.0
    proc = subprocess.Popen(
        tenant_argv, stdout=subprocess.PIPE, text=True,
        env=chaos.child_env(), cwd=REPO,
    )
    try:
        from gene2vec_tpu.serve.fleet import read_contract_line

        info = read_contract_line(proc, 180.0)
        turl = info["url"]
        replica_url = info["replica_urls"][0]
        health = _http_json(replica_url + "/healthz", timeout=10.0)
        assert health.get("tenancy", {}).get("default_rate") == 50.0, (
            f"replica healthz shows no tenancy: {health}"
        )

        import threading

        counts = {
            victim: {"n": 0, "ok": 0, "rejected": 0, "lat": []},
            abuser: {"n": 0, "ok": 0, "rejected": 0, "lat": []},
        }
        tlock = threading.Lock()
        stop_at = time.monotonic() + tduration_s

        def tenant_worker(tenant: str, pace_s: float, widx: int) -> None:
            wrng = np.random.RandomState(seed + 900 + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                body = json.dumps(
                    {"genes": [g], "k": 4}
                ).encode("utf-8")
                req = urllib.request.Request(
                    turl + "/v1/similar", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Tenant": tenant},
                    method="POST",
                )
                t0 = time.monotonic()
                try:
                    with urllib.request.urlopen(req, timeout=10.0) as r:
                        r.read()
                        status = r.status
                except urllib.error.HTTPError as e:
                    e.read()
                    e.close()
                    status = e.code
                except Exception:
                    status = 0
                dur_ms = (time.monotonic() - t0) * 1000.0
                with tlock:
                    c = counts[tenant]
                    c["n"] += 1
                    if status == 200:
                        c["ok"] += 1
                        c["lat"].append(dur_ms)
                    elif status == 429:
                        c["rejected"] += 1
                if pace_s > 0:
                    time.sleep(pace_s)

        # the victim paces at ~20 rps (well inside its 50 rps bucket);
        # the abuser floods unpaced from 8 workers — hundreds of rps
        # against the same 50 rps default bucket
        tenant_threads = [
            threading.Thread(
                target=tenant_worker, args=(victim, 0.05, 0),
                daemon=True,
            )
        ] + [
            threading.Thread(
                target=tenant_worker, args=(abuser, 0.0, 1 + w),
                daemon=True,
            )
            for w in range(8)
        ]
        log(f"tenant isolation: {victim} paced vs {abuser} flooding "
            f"for {tduration_s:g}s")
        for t in tenant_threads:
            t.start()
        for t in tenant_threads:
            t.join(timeout=tduration_s + 60.0)

        v, a = counts[victim], counts[abuser]
        victim_availability = v["ok"] / max(v["n"], 1)
        v["lat"].sort()
        victim_p99_ms = (
            v["lat"][min(len(v["lat"]) - 1, int(0.99 * len(v["lat"])))]
            if v["lat"] else None
        )
        # the labeled rejection series must exist on the replica: WHO
        # was shed is the whole point of the tenant label
        labeled = _parse_labeled_counters(
            urllib.request.urlopen(replica_url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        abuser_series = labeled.get(
            ("serve_rejected_total", (("tenant", abuser),))
        )
        log(f"tenant isolation: {victim} availability "
            f"{victim_availability:.4f} over {v['n']} requests "
            f"(p99 {victim_p99_ms} ms); {abuser} sent {a['n']}, "
            f"shed {a['rejected']} as 429 "
            f"(labeled series: {abuser_series})")
        assert v["n"] >= tduration_s * 5, (
            f"victim sent suspiciously few requests ({v['n']})"
        )
        assert a["rejected"] > 0, (
            "the abusive tenant was never rejected — quotas are not "
            "enforcing"
        )
        assert abuser_series is not None and abuser_series > 0, (
            f"serve_rejected_total{{tenant={abuser!r}}} missing from "
            "the replica's /metrics"
        )
        min_victim = float(budget.get("min_victim_availability", 0.99))
        assert victim_availability >= min_victim, (
            f"victim tenant availability {victim_availability:.4f} "
            f"below budget {min_victim}"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    result = {
        "min_replicas": min_replicas,
        "max_replicas": max_replicas,
        "scrape_interval_s": scrape_s,
        "scale_up_detection_ticks": detection_ticks,
        "scale_up_detection_s": round(detection_s, 3),
        "scale_up_completed_s": round(scale_up_completed_s, 2),
        "scale_down_s": round(scale_down_s, 2),
        "drain_timeouts": drain_timeouts,
        "ramp_workers": ramp_workers,
        "ramp_requests": ramp_counts["n"],
        "ramp_rejected_429": ramp_counts["rejected"],
        "lightload_requests": light["n"],
        "dropped_answers": light["dropped"],
        "wrong_answers": light["wrong"],
        "mixed_iteration_answers": light["mixed"],
        "steady_state_ticks": steady_ticks,
        "steady_state_scale_actions": steady_actions,
        "victim_tenant": victim,
        "abusive_tenant": abuser,
        "victim_requests": v["n"],
        "victim_ok": v["ok"],
        "victim_tenant_availability": round(victim_availability, 5),
        "victim_p99_ms": (
            round(victim_p99_ms, 2) if victim_p99_ms is not None else None
        ),
        "abuser_requests": a["n"],
        "abuser_rejected_429": a["rejected"],
        "tenant_rejections_labeled": True,
        "budget": {k: val for k, val in budget.items()
                   if not k.startswith("_")},
    }
    assert detection_ticks <= max_ticks, (
        f"scale-up detection took {detection_ticks} tick(s), budget "
        f"{max_ticks:g}"
    )
    assert light["n"] >= 10, (
        f"suspiciously little light load ({light['n']} requests) — "
        "the scale-down window was never really exercised"
    )
    assert light["dropped"] == 0, (
        f"{light['dropped']} request(s) dropped during scale-down — "
        "the drain is not zero-drop"
    )
    assert light["wrong"] == 0, (
        f"{light['wrong']} wrong answer(s) during scale actions"
    )
    assert light["mixed"] == 0, (
        f"{light['mixed']} mixed-iteration answer(s) during scale "
        "actions"
    )
    assert steady_actions == 0, (
        f"{steady_actions} scale action(s) in the steady-state window "
        "— the fleet is flapping"
    )
    return result


# -- phase: async checkpoint overhead ---------------------------------------


def drill_async_overhead(tmp: str, budget: dict) -> dict:
    import dataclasses

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.obs.trace import read_events
    from gene2vec_tpu.sgns.train import SGNSTrainer

    vocab_size = int(budget["vocab"])
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    pairs = rng.choice(
        vocab_size, size=(int(budget["num_pairs"]), 2), p=p
    ).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size)
    corpus = PairCorpus(
        Vocab([f"G{i}" for i in range(vocab_size)], counts.astype(np.int64)),
        pairs,
    )
    base = SGNSConfig(
        dim=int(budget["dim"]), batch_pairs=int(budget["batch_pairs"]),
        num_iters=int(budget["num_iters"]),
        txt_output=bool(budget.get("txt_output", True)),
    )

    def overhead(async_on: bool) -> float:
        cfg = dataclasses.replace(base, async_checkpoint=async_on)
        d = os.path.join(tmp, f"overhead_{'async' if async_on else 'sync'}")
        SGNSTrainer(corpus, cfg).run(d, log=lambda s: None)
        spans = {"iteration": 0.0, "checkpoint": 0.0}
        for e in read_events(os.path.join(d, "events.jsonl")):
            if e.get("type") == "span_end" and e.get("name") in spans:
                spans[e["name"]] += float(e.get("dur", 0.0))
        return spans["checkpoint"] / max(spans["iteration"], 1e-9)

    sync_frac = overhead(False)
    async_frac = overhead(True)
    log(f"checkpoint span / epoch wall: sync {sync_frac:.4f}, "
        f"async {async_frac:.4f} (budget {budget['max_overhead_fraction']})")
    assert async_frac < float(budget["max_overhead_fraction"]), (
        f"async checkpoint overhead {async_frac:.4f} exceeds "
        f"{budget['max_overhead_fraction']}"
    )
    return {
        "geometry": {k: budget[k] for k in
                     ("dim", "vocab", "batch_pairs", "num_pairs", "num_iters")},
        "sync_overhead_fraction": round(sync_frac, 5),
        "async_overhead_fraction": round(async_frac, 5),
        "max_overhead_fraction": budget["max_overhead_fraction"],
    }


# -- driver ------------------------------------------------------------------


# -- phase: fleet-sharded index serving --------------------------------------


def _shard_merge_bench(budget: dict, smoke: bool, seed: int) -> dict:
    """The scatter-merge half of the shard phase, in-process: split a
    synthetic clustered table (10M rows at the budget recipe; reduced
    under --smoke) into contiguous row shards, build each shard's OWN
    IVF+int8 index exactly as a shard replica does, run queries through
    the per-shard engines + the cross-process merge, and score recall
    against the exact full-table oracle — all shards up AND with one
    shard removed from the merge (graceful degradation must track the
    dead shard's row fraction)."""
    import jax.numpy as jnp

    from bench import _ann_clustered_table
    from gene2vec_tpu.parallel.sharding import (
        merge_shard_topk,
        shard_ranges,
    )
    from gene2vec_tpu.serve.ann import build_index
    from gene2vec_tpu.serve.engine import SimilarityEngine

    recipe = budget["recipe"]
    rows = 64000 if smoke else int(recipe["rows"])
    clusters = 256 if smoke else int(recipe["clusters"])
    dim = int(recipe["dim"])
    shards = int(recipe["shards"])
    k = int(recipe["k"])
    n_queries = 128 if smoke else int(recipe["queries"])
    nprobe = int(recipe["nprobe"])
    rescore_mult = int(recipe["rescore_mult"])
    latency_reps = 30 if smoke else 100

    log(f"shard bench: {rows:,} x {dim} over {shards} shards "
        f"(clusters {clusters}, nprobe {nprobe})")
    t_build0 = time.monotonic()
    table = _ann_clustered_table(rows, dim, clusters, seed)
    qrng = np.random.RandomState(seed + 1)
    q_idx = qrng.randint(0, rows, n_queries)
    queries = np.ascontiguousarray(table[q_idx])

    # exact oracle: chunked full-table top-k (a merge of chunk-local
    # top-ks IS the exact answer — merge_shard_topk is exact).
    # argpartition + a small sort per chunk: a full 134M-element
    # argsort per chunk takes this single-core host ~a minute each
    def oracle_rows(cols_ranges, kk):
        parts = []
        step = 262144
        for s0, e0 in cols_ranges:
            for s in range(s0, e0, step):
                e = min(s + step, e0)
                scores = (queries @ table[s:e].T).astype(np.float32)
                lk = min(kk, e - s)
                cand = np.argpartition(
                    -scores, lk - 1, axis=1
                )[:, :lk]
                cs = np.take_along_axis(scores, cand, axis=1)
                order = np.argsort(-cs, axis=1, kind="stable")
                parts.append((
                    np.take_along_axis(cs, order, axis=1),
                    np.take_along_axis(cand, order, axis=1)
                    .astype(np.int64) + s,
                ))
        return merge_shard_topk(parts, kk)[1]

    t0 = time.monotonic()
    oracle = oracle_rows([(0, rows)], k)
    oracle_s = time.monotonic() - t0
    log(f"exact oracle over {rows:,} rows in {oracle_s:.1f}s")

    # per-shard replicas, in miniature: slice + per-shard IVF index +
    # the same bucketed engine a shard replica serves from
    ranges = shard_ranges(rows, shards)
    per_shard_clusters = max(8, clusters // shards)
    shard_engines = []
    for s, e in ranges:
        sl = np.ascontiguousarray(table[s:e])
        index = build_index(
            sl, "ivf", clusters=per_shard_clusters, seed=seed,
        )
        engine = SimilarityEngine(
            max_batch=max(1, n_queries), index="ivf",
            nprobe=nprobe, rescore_mult=rescore_mult,
        )
        shard_engines.append((engine, index, jnp.asarray(sl), (s, e)))
    build_s = time.monotonic() - t_build0
    log(f"{shards} shard indexes built "
        f"({per_shard_clusters} clusters each) in {build_s:.1f}s total")

    def scatter(kk, exclude=None, qs=None):
        qs = queries if qs is None else qs
        parts = []
        for i, (engine, index, unit, (s, e)) in enumerate(
            shard_engines
        ):
            if i == exclude:
                continue
            scores, lidx = engine.top_k_ann(
                index, unit, qs, min(kk, e - s)
            )
            parts.append((scores, lidx.astype(np.int64) + s))
        return merge_shard_topk(parts, kk)[1]

    def recall(got, want):
        hits = sum(
            len(set(map(int, g)) & set(map(int, w)))
            for g, w in zip(got, want)
        )
        return hits / float(want.shape[0] * want.shape[1])

    merged = scatter(k)
    recall_all = recall(merged, oracle)

    dead = 0
    dead_frac = (ranges[dead][1] - ranges[dead][0]) / float(rows)
    degraded_recall = recall(scatter(k, exclude=dead), oracle)
    log(f"recall@{k}: all-up {recall_all:.4f}, shard {dead} dead "
        f"{degraded_recall:.4f} (row fraction {dead_frac:.3f})")

    # single-query latency through the whole scatter+merge (the shard
    # kernels run sequentially in-process — an upper bound on the
    # parallel-fleet scatter, which pays max-over-shards, not the sum)
    scatter(k, qs=queries[:1])  # warm the batch-1 bucket per shard
    lat = []
    for i in range(latency_reps):
        q = queries[i % n_queries: i % n_queries + 1]
        t0 = time.perf_counter()
        scatter(k, qs=q)
        lat.append((time.perf_counter() - t0) * 1000.0)
    arr = np.asarray(lat)
    out = {
        "rows": rows, "dim": dim, "shards": shards, "k": k,
        "queries": n_queries, "index": "ivf", "nprobe": nprobe,
        "rescore_mult": rescore_mult, "clusters": clusters,
        "per_shard_clusters": per_shard_clusters,
        "recall_at_10": round(float(recall_all), 5),
        "degraded_recall_at_10": round(float(degraded_recall), 5),
        "dead_shard_row_fraction": round(float(dead_frac), 5),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "latency_reps": latency_reps,
        "oracle_seconds": round(oracle_s, 2),
        "build_seconds": round(build_s, 2),
        "latency_model": "sequential-shard-sum (upper bound)",
    }
    if not smoke:
        assert recall_all >= float(budget["min_recall_at_10"]), (
            f"all-shards-up recall {recall_all:.4f} below budget"
        )
        tol = float(budget["recall_degradation_tolerance"])
        assert abs((recall_all - degraded_recall) - dead_frac) <= tol, (
            f"degradation {recall_all - degraded_recall:.4f} does not "
            f"track row fraction {dead_frac:.4f}"
        )
        assert out["p99_ms"] <= float(budget["max_p99_ms"]), (
            f"merged p99 {out['p99_ms']}ms over budget"
        )
    return out


def _shard_oracle(emb: np.ndarray, tokens, qvec, k: int, cols,
                  exclude_token=None):
    """Exact neighbor-token list for one query over the rows in
    ``cols`` — the drill's local referee for full AND degraded
    (restricted-to-live-shards) answers."""
    from gene2vec_tpu.serve.registry import l2_normalize

    unit = l2_normalize(emb)
    q = l2_normalize(np.asarray([qvec], np.float32))[0]
    cols = np.asarray(sorted(cols))
    scores = unit[cols] @ q
    order = np.argsort(-scores, kind="stable")
    out = []
    for j in order:
        tok = tokens[int(cols[j])]
        if tok == exclude_token:
            continue
        out.append(tok)
        if len(out) >= k:
            break
    return out


def drill_shard(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """The fleet-sharded serving phase: the in-process 10M merge bench
    plus the real-CLI HTTP drill — SIGKILL one shard mid-load (degraded
    200s, never 5xx; recall recovers after restart), swap-under-load
    through the shard-atomic stage/flip (zero wrong / mixed-iteration
    answers), a slow-loris shard (per-shard deadline fires, p99 stays
    bounded), and the replicated-shard failover scenario
    (_shard_failover_drill: one dead sibling costs NOTHING, a dead
    group degrades honestly)."""
    import threading

    from gene2vec_tpu.obs import flight as flight_mod
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    result: dict = {"bench": _shard_merge_bench(budget, smoke, seed)}

    shards = int(budget.get("http_shards", 2))
    vocab, dim, k = 60, 8, 4
    export_dir = os.path.join(tmp, "shard_export")
    _write_iteration(export_dir, 1, vocab_size=vocab, dim=dim)
    # _write_iteration derives the table from RandomState(iteration):
    # recompute it locally so the drill can referee every answer
    embs = {1: np.random.RandomState(1).randn(vocab, dim)
            .astype(np.float32)}
    tokens = [f"G{i}" for i in range(vocab)]
    duration_s = 6.0 if smoke else 10.0
    workers = 3

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir,
        "--shard-by-rows", str(shards),
        "--port", "0", "--health-interval", "0.25",
        "--unhealthy-after", "2", "--backoff-base", "0.3",
        "--swap-interval", "0.4", "--scrape-interval", "0.5",
        "--proxy-timeout-ms", "4000",
        "--shard-deadline-ms", "1500",
        "--seed", str(seed),
    ]
    log(f"spawning sharded fleet: {shards} row shards over "
        f"{vocab} rows")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        ranges = [tuple(r) for r in info["shards"]["ranges"]]
        assert info["shards"]["total_rows"] == vocab
        log(f"sharded front door at {url}; ranges {ranges}")

        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=3, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )

        def oracle(it, qvec, kk, live_shards, exclude_token=None):
            cols = [
                c for si in live_shards
                for c in range(ranges[si][0], ranges[si][1])
            ]
            return _shard_oracle(
                embs[it], tokens, qvec, kk, cols, exclude_token
            )

        query_genes = [f"G{i}" for i in range(0, vocab, 4)]
        all_shards = list(range(shards))

        # E2E merge sanity + qvec warm-up: the front door's answer for
        # every query gene must equal the local exact oracle
        for g in query_genes:
            r = client.request(
                "/v1/similar", {"genes": [g], "k": k}, timeout_s=10.0
            )
            assert r.ok, f"warmup query failed: {r.error_class}"
            doc = r.doc
            assert doc["degraded"] is False
            got = [n["gene"] for n in doc["results"][0]["neighbors"]]
            want = oracle(
                1, embs[1][int(g[1:])], k, all_shards, exclude_token=g
            )
            assert got == want, (
                f"scatter answer for {g} diverges from the exact "
                f"oracle: {got} vs {want}"
            )
        log(f"{len(query_genes)} scatter answers match the exact "
            "oracle end-to-end")

        # ---- sub-phase A: SIGKILL one shard mid-load ----------------
        counts = {"total": 0, "ok": 0, "degraded": 0, "failed": 0,
                  "wrong": 0, "mixed": 0, "server_5xx": 0,
                  "degraded_wrong": 0, "unresolved": 0,
                  "attempts": 0, "retries": 0}
        degraded_recalls = []
        trace_ids = []
        lock = threading.Lock()
        stop_at = time.monotonic() + duration_s
        victim_shard = 1
        live_after_kill = [s for s in all_shards if s != victim_shard]

        def check_answer(doc, it_expected, qvec, gene, killed) -> None:
            """Referee one 200 body against the local oracle (full or
            restricted to the shards that answered)."""
            it = doc["model"]["iteration"]
            if it != it_expected:
                counts["mixed"] += 1
                return
            res0 = doc["results"][0]
            got = [n["gene"] for n in res0["neighbors"]]
            if doc.get("degraded"):
                counts["degraded"] += 1
                if res0.get("degraded") and not got:
                    # honest empty answer: the query gene's owner is
                    # dead and its vector was never cached — a partial
                    # answer with nothing to merge, flagged as such
                    counts["unresolved"] += 1
                    counts["ok"] += 1
                    return
                answered = doc["shards"]["indexes"]
                want = oracle(it, qvec, k, answered, exclude_token=gene)
                if got != want:
                    counts["degraded_wrong"] += 1
                full = oracle(it, qvec, k, all_shards,
                              exclude_token=gene)
                degraded_recalls.append(
                    len(set(got) & set(full)) / float(k)
                )
            else:
                want = oracle(it, qvec, k, all_shards,
                              exclude_token=gene)
                if got != want:
                    counts["wrong"] += 1
                    return
            counts["ok"] += 1

        def worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                use_gene = wrng.rand() < 0.5
                row = int(wrng.randint(vocab))
                if use_gene:
                    gene = tokens[row]
                    body = {"genes": [gene], "k": k}
                else:
                    gene = None
                    body = {"vectors": [[float(x)
                                         for x in embs[1][row]]],
                            "k": k}
                r = client.request("/v1/similar", body, timeout_s=6.0)
                with lock:
                    counts["total"] += 1
                    counts["attempts"] += r.attempts
                    counts["retries"] += r.retries
                    if r.trace_id:
                        trace_ids.append(r.trace_id)
                    if r.status >= 500 and r.target is not None:
                        counts["server_5xx"] += 1
                    if not r.ok or r.doc is None:
                        counts["failed"] += 1
                        continue
                    check_answer(r.doc, 1, embs[1][row], gene,
                                 killed=True)

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        time.sleep(duration_s / 3.0)
        victim_pid = info["replica_pids"][victim_shard]
        log(f"SIGKILL shard {victim_shard} (pid {victim_pid}) mid-load")
        os.kill(victim_pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=duration_s + 30.0)

        availability = counts["ok"] / max(counts["total"], 1)
        prom = _parse_prom_counters(
            urllib.request.urlopen(url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        amplification = (
            (counts["attempts"] + prom.get(
                "fleet_client_retries_total", 0.0
            ) + prom.get("fleet_client_hedges_total", 0.0))
            / max(counts["total"], 1)
        )
        dead_frac = (
            (ranges[victim_shard][1] - ranges[victim_shard][0])
            / float(vocab)
        )
        mean_deg_recall = (
            float(np.mean(degraded_recalls)) if degraded_recalls
            else None
        )
        log(
            f"dead-shard window: availability {availability:.4f} over "
            f"{counts['total']} requests, {counts['degraded']} "
            f"degraded (mean recall {mean_deg_recall}), "
            f"{counts['server_5xx']} server 5xx, amplification "
            f"{amplification:.3f}"
        )
        assert counts["total"] >= workers * duration_s / 2, (
            "suspiciously few requests completed — the load loop wedged"
        )
        assert counts["server_5xx"] == 0, (
            f"{counts['server_5xx']} 5xx responses — a dead shard must "
            "degrade, never fail the query"
        )
        assert counts["degraded"] > 0, (
            "no degraded responses observed — the kill window missed"
        )
        assert counts["wrong"] == 0 and counts["degraded_wrong"] == 0, (
            f"{counts['wrong']} full + {counts['degraded_wrong']} "
            "degraded answers diverged from the exact oracle"
        )
        assert counts["mixed"] == 0, (
            f"{counts['mixed']} answers claimed an unexpected "
            "iteration during the dead-shard window"
        )
        assert availability >= float(budget["min_availability"]), (
            f"availability {availability:.4f} below budget"
        )
        assert amplification <= float(
            budget["max_retry_amplification"]
        ), f"retry amplification {amplification:.3f} over budget"
        if mean_deg_recall is not None and len(degraded_recalls) >= 20:
            drop = 1.0 - mean_deg_recall
            assert abs(drop - dead_frac) <= 0.35, (
                f"degraded recall drop {drop:.3f} does not track the "
                f"dead shard's row fraction {dead_frac:.3f}"
            )

        # recovery: the supervisor restarts the shard, the coordinator
        # repairs its epoch, and FULL recall returns
        def recovered():
            try:
                h = _http_json(url + "/healthz", timeout=5.0)
            except Exception:
                return False
            if not all(s["up"] for s in h.get("shards", [])):
                return False
            r = client.request(
                "/v1/similar",
                {"genes": [query_genes[0]], "k": k}, timeout_s=6.0,
            )
            return bool(r.ok and r.doc and not r.doc["degraded"])

        wait_until(recovered, 120.0, interval_s=0.5,
                   what="dead shard restarted + full recall")
        r = client.request(
            "/v1/similar", {"genes": [query_genes[1]], "k": k},
            timeout_s=6.0,
        )
        got = [n["gene"] for n in r.doc["results"][0]["neighbors"]]
        g = query_genes[1]
        assert got == oracle(1, embs[1][int(g[1:])], k, all_shards,
                             exclude_token=g)
        log("shard restarted; full recall recovered")

        # ---- sub-phase B: shard-atomic swap under load --------------
        swap_counts = {"total": 0, "ok": 0, "failed": 0, "wrong": 0,
                       "mixed": 0, "degraded_wrong": 0, "degraded": 0,
                       "server_5xx": 0, "attempts": 0, "retries": 0}
        iterations_seen = set()
        swap_window = 5.0 if smoke else 8.0
        stop_at = time.monotonic() + swap_window
        embs[2] = np.random.RandomState(2).randn(vocab, dim) \
            .astype(np.float32)

        def swap_check(doc, qvec_by_iter, gene) -> None:
            it = doc["model"]["iteration"]
            if it not in qvec_by_iter:
                swap_counts["mixed"] += 1
                return
            iterations_seen.add(it)
            got = [n["gene"] for n in doc["results"][0]["neighbors"]]
            live = (doc["shards"]["indexes"] if doc.get("degraded")
                    else all_shards)
            if doc.get("degraded"):
                swap_counts["degraded"] += 1
            want = oracle(it, qvec_by_iter[it], k, live,
                          exclude_token=gene)
            if got == want:
                swap_counts["ok"] += 1
            else:
                # consistent with the OTHER iteration => a mixed-
                # iteration merge leaked through the epoch fence
                other = [i for i in qvec_by_iter if i != it]
                if other and got == oracle(
                    other[0], qvec_by_iter[other[0]], k, live,
                    exclude_token=gene,
                ):
                    swap_counts["mixed"] += 1
                else:
                    key = ("degraded_wrong" if doc.get("degraded")
                           else "wrong")
                    swap_counts[key] += 1

        def swap_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 100 + widx)
            while time.monotonic() < stop_at:
                row = int(wrng.randint(vocab))
                gene = tokens[row]
                # gene queries resolve per-epoch on the owner shard, so
                # a swap mid-request exercises the whole fence
                r = client.request(
                    "/v1/similar", {"genes": [gene], "k": k},
                    timeout_s=6.0,
                )
                with lock:
                    swap_counts["total"] += 1
                    swap_counts["attempts"] += r.attempts
                    swap_counts["retries"] += r.retries
                    if r.status >= 500 and r.target is not None:
                        swap_counts["server_5xx"] += 1
                    if not r.ok or r.doc is None:
                        swap_counts["failed"] += 1
                        continue
                    swap_check(
                        r.doc,
                        {it: embs[it][row] for it in embs},
                        gene,
                    )

        threads = [
            threading.Thread(target=swap_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        time.sleep(1.0)
        _write_iteration(export_dir, 2, vocab_size=vocab, dim=dim)
        log("iteration 2 exported mid-load; coordinator should stage "
            "+ flip every shard as one version")
        for t in threads:
            t.join(timeout=swap_window + 30.0)

        def swapped():
            r = client.request(
                "/v1/similar", {"genes": [query_genes[0]], "k": k},
                timeout_s=6.0,
            )
            return bool(
                r.ok and r.doc
                and r.doc["model"]["iteration"] == 2
                and not r.doc["degraded"]
            )

        wait_until(swapped, 60.0, interval_s=0.5,
                   what="shard-atomic swap to iteration 2")
        prom = _parse_prom_counters(
            urllib.request.urlopen(url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        assert prom.get("fleet_swap_flips_total", 0.0) >= 1, (
            "the coordinator never flipped — swap did not happen "
            "through the shard-atomic path"
        )
        log(
            f"swap window: {swap_counts['total']} requests, "
            f"iterations seen {sorted(iterations_seen)}, "
            f"{swap_counts['mixed']} mixed, {swap_counts['wrong']} "
            f"wrong, flips {int(prom.get('fleet_swap_flips_total', 0))}"
        )
        assert swap_counts["server_5xx"] == 0, (
            f"{swap_counts['server_5xx']} 5xx during the swap window"
        )
        assert swap_counts["mixed"] == 0, (
            f"{swap_counts['mixed']} answers crossed the epoch fence "
            "(mixed-iteration merge)"
        )
        assert swap_counts["wrong"] == 0 and (
            swap_counts["degraded_wrong"] == 0
        ), "answers diverged from their claimed iteration's oracle"

        # ---- trace: the scatter fan-out is one span tree ------------
        time.sleep(1.0)
        scatter_trace = None
        for tid in trace_ids[-40:]:
            doc = flight_mod.collect_trace(export_dir, tid)
            names, _ = _trace_tree_facts(doc)
            if {"proxy_scatter", "client_attempt",
                    "serve_request"} <= names:
                scatter_trace = tid
                break
        assert scatter_trace is not None, (
            "no trace reassembled with proxy_scatter -> client_attempt "
            "-> serve_request (the scatter fan-out is invisible)"
        )
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "trace",
             export_dir, scatter_trace],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0 and "proxy_scatter" in cli.stdout, (
            f"cli.obs trace missing the scatter span:\n{cli.stdout}"
        )
        log(f"scatter trace {scatter_trace} reassembled via cli.obs "
            "trace (sibling shard attempts under proxy_scatter)")

        result["drill"] = {
            "shards": shards,
            "vocab": vocab,
            "duration_s": duration_s,
            "requests": counts["total"],
            "ok": counts["ok"],
            "failed": counts["failed"],
            "degraded_responses": counts["degraded"],
            "unresolved_responses": counts["unresolved"],
            "degraded_mean_recall": mean_deg_recall,
            "dead_shard_row_fraction": round(dead_frac, 4),
            "availability": round(availability, 5),
            "server_5xx": counts["server_5xx"],
            "wrong_answers": (
                counts["wrong"] + counts["degraded_wrong"]
                + swap_counts["wrong"] + swap_counts["degraded_wrong"]
            ),
            "mixed_iteration_answers": (
                counts["mixed"] + swap_counts["mixed"]
            ),
            "retry_amplification": round(amplification, 4),
            "recovered_full_recall": True,
            "swap": {
                "requests": swap_counts["total"],
                "iterations_seen": sorted(iterations_seen),
                "degraded": swap_counts["degraded"],
                "flips": int(prom.get("fleet_swap_flips_total", 0)),
                "stage_failures": int(
                    prom.get("fleet_swap_stage_failures_total", 0)
                ),
            },
            "scatter_trace_id": scatter_trace,
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    result["drill"]["slow_loris"] = _shard_slow_loris(
        tmp, smoke, budget, seed
    )
    fo = _shard_failover_drill(tmp, smoke, budget, seed)
    result["drill"]["failover"] = fo
    # the drill-wide integrity tallies cover the failover windows too:
    # passes_shard gates these sums, so a wrong answer in the
    # replicated grid can never hide behind its own sub-section
    result["drill"]["wrong_answers"] += (
        fo["wrong_answers"] + fo["both_dead"]["degraded_wrong"]
        + fo["both_dead"]["wrong_answers"]
    )
    result["drill"]["mixed_iteration_answers"] += (
        fo["mixed_iteration_answers"]
        + fo["both_dead"]["mixed_iteration_answers"]
    )
    result["drill"]["server_5xx"] += (
        fo["server_5xx"] + fo["both_dead"]["server_5xx"]
    )
    return result


def _shard_slow_loris(tmp: str, smoke: bool, budget: dict,
                      seed: int) -> dict:
    """A SLOW shard (injected latency far past the per-shard deadline,
    scoped to the scatter data plane so health probes stay green): the
    per-shard deadline must fire, every answer degrades to the live
    shards — never a 5xx — and p99 stays bounded by the deadline, not
    the fault."""
    from gene2vec_tpu.resilience.faults import FaultSpec
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    shards = int(budget.get("http_shards", 2))
    vocab, dim, k = 40, 8, 4
    export_dir = os.path.join(tmp, "shard_loris_export")
    _write_iteration(export_dir, 1, vocab_size=vocab, dim=dim)
    deadline_ms = 600.0
    faults = FaultSpec(
        seed=seed, latency_p=1.0, latency_ms=3000.0,
        route_prefix="/v1/shard/topk",
    )
    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir,
        "--shard-by-rows", str(shards),
        "--port", "0", "--health-interval", "0.25",
        "--swap-interval", "0.5", "--scrape-interval", "0",
        "--alert-rules", "none",
        "--proxy-timeout-ms", "4000",
        "--shard-deadline-ms", str(deadline_ms),
        "--seed", str(seed),
        "--replica-arg", "0:--faults",
        "--replica-arg", f"0:{faults.to_json()}",
    ]
    log(f"slow-loris fleet: shard 0 injects {faults.latency_ms:.0f}ms "
        f"on the scatter route; per-shard deadline {deadline_ms:.0f}ms")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        client = ResilientClient(
            [url],
            RetryPolicy(max_attempts=2, default_timeout_s=6.0,
                        read_timeout_s=6.0),
        )
        n = 12 if smoke else 25
        latencies = []
        degraded = server_5xx = failed = 0
        rng = np.random.RandomState(seed)
        emb = np.random.RandomState(1).randn(vocab, dim) \
            .astype(np.float32)
        for i in range(n):
            row = int(rng.randint(vocab))
            r = client.request(
                "/v1/similar",
                {"vectors": [[float(x) for x in emb[row]]], "k": k},
                timeout_s=6.0,
            )
            latencies.append(r.latency_s * 1000.0)
            if r.status >= 500 and r.target is not None:
                server_5xx += 1
            if not r.ok or r.doc is None:
                failed += 1
                continue
            if r.doc.get("degraded"):
                degraded += 1
        prom = _parse_prom_counters(
            urllib.request.urlopen(url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        leg_deadlines = prom.get("fleet_shard_leg_deadline_total", 0.0)
        arr = np.asarray(latencies)
        p99 = float(np.percentile(arr, 99))
        availability = (n - failed) / float(n)
        log(
            f"slow loris: {degraded}/{n} degraded, p99 {p99:.0f}ms, "
            f"{int(leg_deadlines)} shard-leg deadlines, "
            f"{server_5xx} server 5xx"
        )
        assert server_5xx == 0, "a slow shard must degrade, never 5xx"
        assert degraded >= n * 0.8, (
            f"only {degraded}/{n} answers degraded — the slow shard's "
            "legs are not being reaped by the per-shard deadline"
        )
        assert leg_deadlines >= 1, (
            "fleet_shard_leg_deadline_total never incremented"
        )
        assert availability >= float(budget["min_availability"]), (
            f"slow-loris availability {availability:.4f} below budget"
        )
        # the whole point: p99 is bounded by the deadline machinery
        # (deadline + retry + merge overhead), NOT the 3s fault
        assert p99 <= 2900.0, (
            f"p99 {p99:.0f}ms — the per-shard deadline is not bounding "
            "the slow shard"
        )
        return {
            "requests": n,
            "degraded": degraded,
            "availability": round(availability, 5),
            "server_5xx": server_5xx,
            "p99_ms": round(p99, 1),
            "shard_leg_deadlines": int(leg_deadlines),
            "injected_latency_ms": faults.latency_ms,
            "shard_deadline_ms": deadline_ms,
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


def _shard_failover_drill(tmp: str, smoke: bool, budget: dict,
                          seed: int) -> dict:
    """Replicated shards (--replicas-per-shard): SIGKILL one sibling of
    a 2-replica shard under verified load — the scatter must fail over
    to the live sibling within the leg deadline (availability 1.0,
    ZERO degraded answers, 0 wrong/mixed, the shard-redundancy-lost
    alert fires and clears) — then kill BOTH siblings and the PR-13
    degraded contract must hold unchanged.  Also exercises the
    cross-shard /v1/interaction path end-to-end (the 501 is gone)."""
    import threading

    from gene2vec_tpu.obs import flight as flight_mod
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    fo_budget = budget.get("failover") or {}
    shards = int(budget.get("http_shards", 2))
    rps = int(fo_budget.get("replicas_per_shard", 2))
    vocab, dim, k = 60, 8, 4
    export_dir = os.path.join(tmp, "shard_failover_export")
    _write_iteration(export_dir, 1, vocab_size=vocab, dim=dim)
    embs = {1: np.random.RandomState(1).randn(vocab, dim)
            .astype(np.float32)}
    tokens = [f"G{i}" for i in range(vocab)]
    duration_s = 5.0 if smoke else 8.0
    workers = 3

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir,
        "--shard-by-rows", str(shards),
        "--replicas-per-shard", str(rps),
        "--port", "0", "--health-interval", "0.25",
        "--unhealthy-after", "2", "--backoff-base", "0.3",
        "--swap-interval", "0.5", "--scrape-interval", "0.25",
        "--proxy-timeout-ms", "4000",
        "--shard-deadline-ms", "1500",
        "--seed", str(seed),
    ]
    log(f"spawning replicated-shard fleet: {shards} shards x {rps} "
        f"replicas over {vocab} rows")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 240.0)
        url = info["url"]
        run_dir = info["run_dir"]
        ranges = [tuple(r) for r in info["shards"]["ranges"]]
        groups = {
            int(s): slots
            for s, slots in info["shards"]["groups"].items()
        }
        assert info["shards"]["replicas_per_shard"] == rps
        assert all(len(slots) == rps for slots in groups.values()), (
            f"grid accounting broke: {groups}"
        )
        log(f"replicated front door at {url}; groups {groups}")

        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=3, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )

        def oracle(it, qvec, kk, live_shards, exclude_token=None):
            cols = [
                c for si in live_shards
                for c in range(ranges[si][0], ranges[si][1])
            ]
            return _shard_oracle(
                embs[it], tokens, qvec, kk, cols, exclude_token
            )

        all_shards = list(range(shards))
        query_genes = [f"G{i}" for i in range(0, vocab, 4)]
        # two warm passes: round-robin spreads legs over BOTH siblings
        # of each shard, so every cell's engine is jit-warm before the
        # failover window (a cold sibling would smear the p99)
        for _pass in range(2):
            for g in query_genes:
                r = client.request(
                    "/v1/similar", {"genes": [g], "k": k},
                    timeout_s=10.0,
                )
                assert r.ok and not r.doc["degraded"]
                got = [n["gene"]
                       for n in r.doc["results"][0]["neighbors"]]
                want = oracle(1, embs[1][int(g[1:])], k, all_shards,
                              exclude_token=g)
                assert got == want, f"warmup diverged for {g}"

        # cross-shard interaction: a pair spanning shard boundaries
        # scores at the front door — the PR-13 501 is gone
        cross_pair = [tokens[0], tokens[-1]]
        r = client.request(
            "/v1/interaction",
            {"pairs": [cross_pair, [tokens[1], tokens[2]]]},
            timeout_s=10.0,
        )
        assert r.ok, (
            f"/v1/interaction failed on the sharded fleet: "
            f"{r.status} {r.error_class}"
        )
        idoc = r.doc
        assert not idoc.get("degraded")
        assert "trained_head" in idoc
        assert len(idoc["scores"]) == 2 and all(
            isinstance(s["score"], float) and 0.0 <= s["score"] <= 1.0
            for s in idoc["scores"]
        ), f"malformed interaction scores: {idoc['scores']}"
        log(f"cross-shard /v1/interaction answered: {idoc['scores']}")

        # ---- window 1: SIGKILL one sibling; ZERO degraded allowed ---
        counts = {"total": 0, "ok": 0, "degraded": 0, "failed": 0,
                  "wrong": 0, "mixed": 0, "server_5xx": 0,
                  "attempts": 0, "retries": 0}
        latencies: list = []
        trace_ids: list = []
        lock = threading.Lock()
        stop_at = time.monotonic() + duration_s
        victim_shard = 1
        kill_at = time.monotonic() + duration_s / 3.0

        def worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                row = int(wrng.randint(vocab))
                use_gene = wrng.rand() < 0.5
                gene = tokens[row] if use_gene else None
                body = (
                    {"genes": [gene], "k": k} if use_gene
                    else {"vectors": [[float(x) for x in embs[1][row]]],
                          "k": k}
                )
                r = client.request("/v1/similar", body, timeout_s=6.0)
                with lock:
                    counts["total"] += 1
                    counts["attempts"] += r.attempts
                    counts["retries"] += r.retries
                    latencies.append(r.latency_s * 1000.0)
                    if r.trace_id:
                        # the failover happens INSIDE the fleet process
                        # (scatter-leg sibling attempts), invisible in
                        # this client's attempt count — keep timestamps
                        # so the search below targets the kill window
                        trace_ids.append(
                            (time.monotonic(), r.trace_id)
                        )
                    if r.status >= 500 and r.target is not None:
                        counts["server_5xx"] += 1
                    if not r.ok or r.doc is None:
                        counts["failed"] += 1
                        continue
                    doc = r.doc
                    if doc["model"]["iteration"] != 1:
                        counts["mixed"] += 1
                        continue
                    if doc.get("degraded"):
                        counts["degraded"] += 1
                        continue
                    got = [n["gene"]
                           for n in doc["results"][0]["neighbors"]]
                    want = oracle(1, embs[1][row], k, all_shards,
                                  exclude_token=gene)
                    if got != want:
                        counts["wrong"] += 1
                    else:
                        counts["ok"] += 1

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        time.sleep(max(0.0, kill_at - time.monotonic()))
        victim_slot = groups[victim_shard][0]
        victim_pid = info["replica_pids"][victim_slot]
        log(f"SIGKILL replica slot {victim_slot} (shard {victim_shard},"
            f" pid {victim_pid}) — its sibling must absorb everything")
        killed_at = time.monotonic()
        os.kill(victim_pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=duration_s + 30.0)

        # degraded answers are NOT ok in this window — with a live
        # sibling the contract is zero degradation, so availability
        # here counts exact answers only (no lenient ok+degraded
        # variant: it would be one careless edit away from weakening
        # the zero-degradation assert below)
        strict_availability = counts["ok"] / max(counts["total"], 1)
        p99 = float(np.percentile(np.asarray(latencies), 99))
        log(
            f"failover window: {counts['total']} requests, "
            f"{counts['degraded']} degraded, {counts['failed']} "
            f"failed, {counts['server_5xx']} 5xx, p99 {p99:.1f}ms"
        )
        assert counts["total"] >= workers * duration_s / 2, (
            "suspiciously few requests — the load loop wedged"
        )
        assert counts["degraded"] <= int(
            fo_budget.get("max_degraded_with_live_replica", 0)
        ), (
            f"{counts['degraded']} degraded responses while a sibling "
            "was live — failover must absorb a single replica death"
        )
        assert counts["server_5xx"] == 0
        assert counts["wrong"] == 0 and counts["mixed"] == 0, (
            f"{counts['wrong']} wrong / {counts['mixed']} mixed "
            "answers during failover"
        )
        min_avail = float(fo_budget.get("min_availability", 1.0))
        assert strict_availability >= min_avail, (
            f"failover availability {strict_availability:.4f} < "
            f"{min_avail} — requests were lost, not failed over"
        )
        max_p99 = float(fo_budget.get("max_failover_p99_ms", 2000.0))
        assert p99 <= max_p99, (
            f"failover-window p99 {p99:.1f}ms over budget {max_p99}"
        )

        # the redundancy page fired: shard-redundancy-lost in the
        # fleet run dir's alerts.jsonl (the page that PRECEDES the
        # recall-degradation page — window 1 never degraded)
        def redundancy_fired():
            path = os.path.join(run_dir, "alerts.jsonl")
            if not os.path.exists(path):
                return False
            with open(path) as f:
                return any(
                    '"shard-redundancy-lost"' in line
                    and '"firing"' in line
                    for line in f
                )

        wait_until(redundancy_fired, 30.0, interval_s=0.5,
                   what="shard-redundancy-lost alert firing")
        log("shard-redundancy-lost fired on the sibling's death")

        # a failover trace: one proxy_scatter span whose shard leg
        # carries >= 2 sibling client_attempt hops (the dead pick and
        # the failover) — satellite: cli.obs trace renders the grid
        time.sleep(1.0)
        failover_trace = None
        window_ids = [
            tid for ts, tid in trace_ids
            if killed_at - 0.5 <= ts <= killed_at + 3.0
        ]
        for tid in window_ids:
            doc = flight_mod.collect_trace(export_dir, tid)
            if _scatter_failover_attempts(doc) >= 2:
                failover_trace = tid
                break
        assert failover_trace is not None, (
            "no reassembled trace shows >= 2 sibling client_attempts "
            "under one proxy_scatter span"
        )
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "trace",
             export_dir, failover_trace],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0 and "proxy_scatter" in cli.stdout
        assert cli.stdout.count("client_attempt") >= 2, (
            f"cli.obs trace hides the failover leg:\n{cli.stdout}"
        )
        log(f"failover trace {failover_trace} rendered: sibling "
            "attempts under one proxy_scatter")

        # recovery: the supervisor respawns the sibling, redundancy
        # returns, the alert clears
        def grid_recovered():
            try:
                h = _http_json(url + "/healthz", timeout=5.0)
            except Exception:
                return False
            return all(
                r["up"]
                for s in h.get("shards", [])
                for r in s.get("replicas", [])
            )

        wait_until(grid_recovered, 120.0, interval_s=0.5,
                   what="killed sibling respawned (full grid up)")

        def redundancy_cleared():
            with open(os.path.join(run_dir, "alerts.jsonl")) as f:
                return any(
                    '"shard-redundancy-lost"' in line
                    and '"inactive"' in line
                    for line in f
                )

        wait_until(redundancy_cleared, 60.0, interval_s=0.5,
                   what="shard-redundancy-lost clearing on re-admit")
        log("grid recovered; shard-redundancy-lost cleared")

        # ---- window 2: kill BOTH siblings — the PR-13 degraded
        # contract must be unchanged ----------------------------------
        h = _http_json(url + "/healthz", timeout=5.0)
        shard_entry = next(
            s for s in h["shards"] if s["index"] == victim_shard
        )
        pids = [r["pid"] for r in shard_entry["replicas"] if r["up"]]
        assert len(pids) == rps
        both = {"total": 0, "ok": 0, "degraded": 0, "failed": 0,
                "wrong": 0, "mixed": 0, "server_5xx": 0,
                "degraded_wrong": 0, "unresolved": 0}
        stop_at = time.monotonic() + duration_s
        log(f"SIGKILL BOTH siblings of shard {victim_shard} "
            f"(pids {pids})")
        for pid in pids:
            os.kill(pid, signal.SIGKILL)

        def both_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 50 + widx)
            while time.monotonic() < stop_at:
                row = int(wrng.randint(vocab))
                r = client.request(
                    "/v1/similar",
                    {"vectors": [[float(x) for x in embs[1][row]]],
                     "k": k},
                    timeout_s=6.0,
                )
                with lock:
                    both["total"] += 1
                    if r.status >= 500 and r.target is not None:
                        both["server_5xx"] += 1
                    if not r.ok or r.doc is None:
                        both["failed"] += 1
                        continue
                    doc = r.doc
                    if doc["model"]["iteration"] != 1:
                        both["mixed"] += 1
                        continue
                    got = [n["gene"]
                           for n in doc["results"][0]["neighbors"]]
                    if doc.get("degraded"):
                        both["degraded"] += 1
                        answered = doc["shards"]["indexes"]
                        want = oracle(1, embs[1][row], k, answered)
                        if got != want:
                            both["degraded_wrong"] += 1
                        else:
                            both["ok"] += 1
                    else:
                        want = oracle(1, embs[1][row], k, all_shards)
                        if got != want:
                            both["wrong"] += 1
                        else:
                            both["ok"] += 1

        threads = [
            threading.Thread(target=both_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + 30.0)
        log(
            f"both-dead window: {both['total']} requests, "
            f"{both['degraded']} degraded, {both['degraded_wrong']} "
            f"degraded-wrong, {both['server_5xx']} 5xx"
        )
        assert both["server_5xx"] == 0, (
            "a fully-dead shard group must degrade, never 5xx"
        )
        assert both["degraded"] >= int(
            fo_budget.get("min_both_dead_degraded", 1)
        ), "the both-dead window never landed"
        assert both["wrong"] == 0 and both["degraded_wrong"] == 0
        assert both["mixed"] == 0

        return {
            "shards": shards,
            "replicas_per_shard": rps,
            "duration_s": duration_s,
            "requests": counts["total"],
            "availability": round(strict_availability, 5),
            "degraded_responses": counts["degraded"],
            "p99_ms": round(p99, 1),
            "server_5xx": counts["server_5xx"],
            "wrong_answers": counts["wrong"],
            "mixed_iteration_answers": counts["mixed"],
            "retry_amplification": round(
                counts["attempts"] / max(counts["total"], 1), 4
            ),
            "redundancy_alert_fired": True,
            "redundancy_alert_cleared": True,
            "failover_trace_id": failover_trace,
            "interaction_scores": [
                s["score"] for s in idoc["scores"]
            ],
            "both_dead": {
                "requests": both["total"],
                "degraded_responses": both["degraded"],
                "degraded_wrong": both["degraded_wrong"],
                "server_5xx": both["server_5xx"],
                "wrong_answers": both["wrong"],
                "mixed_iteration_answers": both["mixed"],
            },
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


def _scatter_failover_attempts(doc: dict) -> int:
    """Max client_attempt count under any single proxy_scatter span of
    a reassembled trace — >= 2 means a scatter leg failed over between
    siblings (or retried), rendered under ONE fan-out span."""
    best = 0

    def attempts_below(node: dict) -> int:
        n = 1 if node.get("name") == "client_attempt" else 0
        for sub in node.get("process_spans", []):
            n += attempts_below(sub)
        for child in node.get("children", []):
            n += attempts_below(child)
        return n

    def walk(node: dict) -> None:
        nonlocal best
        if node.get("name") == "proxy_scatter":
            best = max(best, attempts_below(node))
        for sub in node.get("process_spans", []):
            walk(sub)
        for child in node.get("children", []):
            walk(child)

    for root in doc.get("roots", []):
        walk(root)
    return best


# -- phase: the continuous-learning loop -------------------------------------


def _loop_topk_reference(url: str, genes, k: int = 5) -> dict:
    """gene -> (iteration, neighbor tuple) straight from the fleet —
    the per-iteration answer oracle the loop phase verifies against."""
    out = {}
    for g in genes:
        doc = _post_json(
            url + "/v1/similar", {"genes": [g], "k": k}, timeout=15.0
        )
        out[g] = (
            doc["model"]["iteration"],
            tuple(n["gene"] for n in doc["results"][0]["neighbors"]),
        )
    return out


def _post_json(url: str, body: dict, timeout: float = 10.0) -> dict:
    import urllib.request as _rq

    req = _rq.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with _rq.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def drill_loop(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """End-to-end continuous-learning cycle against a real fleet, with
    a REAL SIGKILL injected in every loop state (docs/CONTINUOUS.md):

    1. pretrain a serving model, spawn ``cli.fleet --enable-shadow``
       over it, and start continuous verified light load;
    2. compute an IN-PROCESS control continuation (the deterministic
       adopt+train path ``cli.loop`` runs) — the bit-exactness oracle;
    3. run ``cli.loop`` once per kill state (``--crash-at`` SIGKILLs
       the process the moment that state's journal record commits;
       ``TRAINING_MID`` kills after the first continued iteration),
       resuming from the journal each time, then once to completion;
    4. assert: the fleet adopted the promoted iteration (including a
       gene the old model had never seen), the resumed candidate table
       is BIT-exact vs the control, every load answer matched its
       iteration's reference (ZERO wrong, ZERO mixed), and churn/p99
       delta/decision latency landed inside budgets.json "loop".
    """
    import threading

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io import checkpoint as ckpt_mod
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.loop import ingest as ing
    from gene2vec_tpu.loop import trainer as ltr
    from gene2vec_tpu.serve.fleet import read_contract_line

    os.makedirs(tmp, exist_ok=True)
    serving = os.path.join(tmp, "loop_serving")
    loop_root = os.path.join(tmp, "loop_root")
    replicas = int(budget.get("replicas", 2))
    train_iters = int(budget.get("train_iters", 2))
    shadow_sample = float(budget.get("shadow_sample", 1.0))
    min_shadow = int(budget.get("min_shadow_requests", 30))
    dim, pre_iters, batch_pairs = 16, 10, 256

    # clustered corpus: 3 clusters of 10 genes — enough structure that
    # the tiny-geometry candidate separates held-out pairs well above
    # the 0.7 gate floor and top-k neighborhoods stay mostly stable
    # through two continued iterations (measured churn ~0.3)
    rng = np.random.RandomState(seed)

    def cluster_lines(n: int) -> list:
        out = []
        for _ in range(n):
            c = rng.randint(3)
            a, b = rng.choice(10, 2, replace=False) + 10 * c
            out.append(f"G{a} G{b}")
        return out

    base_lines = cluster_lines(800)
    batch_lines = cluster_lines(80) + [
        "GNEW0 G0", "GNEW0 G3", "GNEW1 G12", "GNEW1 G15",
    ] * 4
    base_file = os.path.join(tmp, "loop_base_pairs.txt")
    batch_file = os.path.join(tmp, "loop_batch_pairs.txt")
    with open(base_file, "w") as f:
        f.write("\n".join(base_lines) + "\n")
    with open(batch_file, "w") as f:
        f.write("\n".join(batch_lines) + "\n")

    cfg = SGNSConfig(
        dim=dim, batch_pairs=batch_pairs, num_iters=pre_iters,
        txt_output=False, seed=1,
    )
    vocab = Vocab.from_pairs([ln.split() for ln in base_lines])
    corpus = PairCorpus(
        vocab, vocab.encode_pairs([ln.split() for ln in base_lines])
    )
    log(f"pretraining serving model ({pre_iters} iters, dim {dim})")
    from gene2vec_tpu.sgns.train import SGNSTrainer

    SGNSTrainer(corpus, cfg).run(serving, log=lambda s: None)

    # the in-process CONTROL continuation: exactly the deterministic
    # adopt+train path cli.loop runs, against a SEPARATE loop root —
    # whatever bytes the kill-riddled chaos cycle converges on must be
    # bit-identical to these
    control_root = os.path.join(tmp, "loop_control_root")
    ing.init_ingest(control_root, vocab)
    ing.ingest_batch(control_root, "seed", base_lines,
                     replaces_base_counts=True)
    ing.ingest_batch(control_root, "b1", batch_lines)
    control_corpus, _held = ing.load_loop_corpus(control_root, 0.2)
    control_cand = os.path.join(tmp, "loop_control_cand")
    control_params, _cb, control_final = ltr.train_candidate(
        serving, control_cand, control_corpus, cfg, train_iters,
        log=lambda s: None,
    )
    control_emb = np.asarray(control_params.emb)
    control_ctx = np.asarray(control_params.ctx)

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", serving, "--replicas", str(replicas),
        "--port", "0", "--health-interval", "0.25",
        "--scrape-interval", "0.5", "--enable-shadow",
        "--seed", str(seed),
        # fast self-swap polls: promotion latency should measure the
        # loop, not a 5 s default poll cadence
        "--serve-arg=--poll-interval", "--serve-arg=0.5",
    ]
    log(f"spawning fleet: {replicas} replicas, shadow canary enabled")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    loop_env = chaos.child_env()
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        assert info.get("shadow"), "fleet contract missing shadow=true"
        log(f"fleet front door at {url}")

        query_genes = [f"G{i}" for i in range(0, 30, 4)]
        reference_old = _loop_topk_reference(url, query_genes)
        old_iter = next(iter(reference_old.values()))[0]
        assert old_iter == pre_iters

        # continuous verified light load through the WHOLE cycle: every
        # answer is later checked against its iteration's reference —
        # zero wrong, zero mixed-iteration
        records = []
        rec_lock = threading.Lock()
        stop_load = threading.Event()

        def load_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 100 + widx)
            while not stop_load.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                try:
                    doc = _post_json(
                        url + "/v1/similar", {"genes": [g], "k": 5},
                        timeout=10.0,
                    )
                    with rec_lock:
                        records.append((
                            g,
                            doc["model"]["iteration"],
                            tuple(
                                n["gene"]
                                for n in doc["results"][0]["neighbors"]
                            ),
                        ))
                except Exception:
                    with rec_lock:
                        records.append((g, None, None))
                time.sleep(0.05)

        workers = [
            threading.Thread(target=load_worker, args=(i,), daemon=True)
            for i in range(3)
        ]
        for w in workers:
            w.start()

        loop_argv = [
            sys.executable, "-m", "gene2vec_tpu.cli.loop",
            "--loop-root", loop_root, "--serving-export", serving,
            "--batch", batch_file, "--batch-id", "b1",
            "--seed-corpus", base_file,
            "--fleet-url", url,
            "--dim", str(dim), "--train-iters", str(train_iters),
            "--batch-pairs", str(batch_pairs), "--sgns-seed", "1",
            "--holdout-frac", "0.2",
            # synthetic-corpus gate band: the canonical [0.862, 0.92]
            # band is calibrated for the real protocol; this geometry
            # measures 0.88 +- 0.03 with a wide floor at 0.7
            "--min-auc", "0.7", "--max-auc", "1.01",
            "--shadow-sample", str(shadow_sample),
            "--shadow-min-requests", str(min_shadow),
            "--shadow-max-wait", "90",
            "--max-churn", str(budget.get("max_answer_churn", 0.5)),
            "--max-p99-delta-ms",
            str(budget.get("max_shadow_p99_delta_ms", 500.0)),
            "--promote-timeout", "90",
        ]
        kill_states = [
            "INGESTING", "TRAINING_MID", "SHADOWING", "PROMOTING",
        ]
        t_cycle0 = time.monotonic()
        for state in kill_states:
            log(f"cycle attempt with SIGKILL at {state}")
            # capture stdout: a run that demotes before its kill state
            # would otherwise print ITS contract JSON into the drill's
            # stdout, breaking the one-JSON-document machine contract
            r = subprocess.run(
                loop_argv + ["--crash-at", state],
                timeout=420, env=loop_env, cwd=REPO,
                stdout=subprocess.PIPE, text=True,
            )
            # died-by-signal (< 0) is the ONLY acceptable outcome: a
            # plain nonzero exit (e.g. rc=3 pre-crash demotion) would
            # journal DEMOTED for this batch id and poison every
            # later attempt with a misleading final-cycle failure
            assert r.returncode < 0, (
                f"--crash-at {state} run exited {r.returncode} "
                f"instead of dying by SIGKILL:\n{(r.stdout or '')[-2000:]}"
            )
        log("final cycle attempt (no kill) — resuming from the journal")
        r = subprocess.run(
            loop_argv, timeout=420, env=loop_env, cwd=REPO,
            stdout=subprocess.PIPE, text=True,
        )
        assert r.returncode == 0, (
            f"final loop cycle failed rc={r.returncode}"
        )
        contract = json.loads(r.stdout.strip().splitlines()[-1])
        assert contract["state"] == "SERVING", contract["state"]
        facts = contract["facts"]
        promoted_iter = facts["PROMOTING"]["promoted_iteration"]
        ingest_to_promoted_s = time.monotonic() - t_cycle0
        assert promoted_iter == control_final

        # the fleet now answers from the new iteration — including a
        # gene the old model had never seen (vocab tail extension end
        # to end)
        reference_new = _loop_topk_reference(url, query_genes)
        for g in query_genes:
            assert reference_new[g][0] == promoted_iter, (
                f"{g}: fleet still on {reference_new[g][0]}"
            )
        new_gene_doc = _post_json(
            url + "/v1/similar", {"genes": ["GNEW0"], "k": 5},
            timeout=15.0,
        )
        assert new_gene_doc["model"]["iteration"] == promoted_iter
        assert new_gene_doc["results"][0]["neighbors"], (
            "new gene answered with no neighbors"
        )

        time.sleep(1.0)
        stop_load.set()
        for w in workers:
            w.join(timeout=10.0)

        # answer integrity: every recorded answer must match ITS
        # iteration's reference exactly — a new-iteration tag with
        # old-iteration neighbors (or vice versa) is a mixed answer
        wrong = mixed = failed = 0
        for g, it, neigh in records:
            if it is None:
                failed += 1
                continue
            if it == old_iter:
                ref = reference_old[g][1]
            elif it == promoted_iter:
                ref = reference_new[g][1]
            else:
                mixed += 1
                continue
            if neigh != ref:
                wrong += 1

        # bit-exactness: the kill-riddled cycle's candidate table ==
        # the uninterrupted in-process control, byte for byte
        cand_dir = os.path.join(loop_root, "candidates", "b1")
        chaos_params, _v, _m = ckpt_mod.load_iteration(
            cand_dir, dim, promoted_iter, table_dtype=None
        )
        resume_bit_exact = bool(
            np.array_equal(np.asarray(chaos_params.emb), control_emb)
            and np.array_equal(np.asarray(chaos_params.ctx), control_ctx)
        )

        shadow_report = (facts.get("SHADOWING") or {}).get("report", {})
        quality = facts.get("QUALITY_GATE") or {}
        walls = contract.get("state_walls", {})
        promoting = walls.get("PROMOTING", {})
        promotion_decision_s = (
            round(promoting["done"] - promoting["enter"], 3)
            if "done" in promoting and "enter" in promoting else None
        )

        result = {
            "replicas": replicas,
            "train_iters": train_iters,
            "shadow_sample": shadow_sample,
            "min_shadow_requests": min_shadow,
            "states_killed": len(kill_states),
            "kill_states": kill_states,
            "promoted": True,
            "promoted_iteration": promoted_iter,
            "new_genes": 2,
            "new_gene_served": True,
            "resume_bit_exact": resume_bit_exact,
            "ingest_to_promoted_s": round(ingest_to_promoted_s, 2),
            "promotion_decision_s": promotion_decision_s,
            "answer_churn": shadow_report.get("answer_churn"),
            "answer_churn_max": shadow_report.get("answer_churn_max"),
            "shadow_p99_delta_ms": shadow_report.get("p99_delta_ms"),
            "shadow_p99_live_ms": shadow_report.get("p99_live_ms"),
            "shadow_p99_shadow_ms": shadow_report.get("p99_shadow_ms"),
            "shadow_scored": shadow_report.get("scored"),
            "quality_auc": quality.get("auc"),
            "verified_requests": len(records),
            "failed_requests": failed,
            "wrong_answers": wrong,
            "mixed_iteration_answers": mixed,
        }
        log(f"loop cycle: {json.dumps(result)}")
        assert resume_bit_exact, (
            "SIGKILL-resumed candidate diverged from the uninterrupted "
            "control"
        )
        assert wrong == 0, f"{wrong} wrong answers during the cycle"
        assert mixed == 0, f"{mixed} mixed-iteration answers"
        churn = result["answer_churn"]
        assert churn is not None and churn <= float(
            budget.get("max_answer_churn", 0.5)
        ), f"answer churn {churn} over budget"
        delta = result["shadow_p99_delta_ms"]
        assert delta is not None and delta <= float(
            budget.get("max_shadow_p99_delta_ms", 500.0)
        ), f"shadow p99 delta {delta} over budget"
        assert promotion_decision_s is not None and (
            promotion_decision_s
            <= float(budget.get("max_promotion_decision_s", 60.0))
        ), f"promotion decision latency {promotion_decision_s}s over budget"
        assert result["shadow_scored"] >= min_shadow
        return result
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
        # reap any candidate replica a killed cli.loop attempt left
        # behind (pids are journaled the moment they spawn)
        try:
            from gene2vec_tpu.loop.promote import LoopJournal, journal_path

            for rec in LoopJournal(
                journal_path(loop_root, "b1"), "b1"
            ).replay():
                pid = (
                    rec.get("facts", {}).get("candidate") or {}
                ).get("pid")
                if pid:
                    try:
                        os.kill(int(pid), signal.SIGKILL)
                    except (OSError, ValueError):
                        pass
        except Exception:
            pass


# -- phase: batch analytics plane (docs/BATCH.md) ----------------------------


def _batch_post_json(url: str, body: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _batch_oracle_topk(unit: np.ndarray, q_rows: np.ndarray,
                       k: int, block: int = 65536) -> np.ndarray:
    """Brute-force cosine top-k neighbor ids for the given query rows,
    self excluded (the /v1/similar contract the batch graph inherits) —
    the referee for both graph geometries.  Chunked over table rows so
    the 1M x nq score matrix never materializes."""
    nq = len(q_rows)
    queries = unit[q_rows]
    best_scores = np.full((nq, k), -np.inf, dtype=np.float32)
    best_ids = np.full((nq, k), -1, dtype=np.int64)
    for s in range(0, unit.shape[0], block):
        sims = unit[s:s + block] @ queries.T
        for qi, row in enumerate(q_rows):
            if s <= row < s + sims.shape[0]:
                sims[row - s, qi] = -np.inf
        kk = min(k, sims.shape[0])
        part = np.argpartition(-sims, kk - 1, axis=0)[:kk]
        for qi in range(nq):
            cand_ids = np.concatenate([best_ids[qi], part[:, qi] + s])
            cand_sc = np.concatenate(
                [best_scores[qi], sims[part[:, qi], qi]]
            )
            keep = np.argsort(-cand_sc, kind="stable")[:k]
            best_ids[qi] = cand_ids[keep]
            best_scores[qi] = cand_sc[keep]
    return best_ids


def _batch_clustered_unit(rows: int, dim: int, clusters: int,
                          seed: int, spread: float = 0.35) -> np.ndarray:
    """Mixture-of-centroids unit table — the bench.py ANN convention
    (trained embedding tables cluster by function; the uniform-random
    adversarial IVF case is covered by the recall harness in
    tests/)."""
    from gene2vec_tpu.serve.registry import l2_normalize

    rng = np.random.RandomState(seed)
    cent = rng.randn(clusters, dim).astype(np.float32)
    assign = rng.randint(0, clusters, rows)
    out = np.empty((rows, dim), np.float32)
    step = 131072  # chunked: rows x dim materializes once, not thrice
    for s in range(0, rows, step):
        b = cent[assign[s:s + step]]
        out[s:s + step] = (
            b + spread * rng.randn(*b.shape).astype(np.float32)
        )
    return l2_normalize(out)


def drill_batch(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """The offline analytics plane (docs/BATCH.md): a full-vocab kNN
    graph built THROUGH the live sharded front door's ``/v1/jobs``
    background lane, SIGKILLed mid-build (front door + orphaned
    replicas reaped by contract pid), resumed by a restarted fleet
    from the journaled cursor, and byte-compared against an
    uninterrupted control built through the SAME scatter path; sampled
    brute-force oracle recall; the mixed-workload phase
    (``scripts/serve_loadgen.py --batch-phase``) proving the
    interactive p99 survives a concurrent graph build; then the
    1M-row IVF scaling measurement in-process.  Run WITHOUT --smoke
    for the committed BENCH_BATCH artifact — a smoke run uses a small
    geometry and is off the pinned recipe."""
    from gene2vec_tpu.batch.artifact import (
        DATA_NAME,
        TOKENS_NAME,
        load_graph,
    )
    from gene2vec_tpu.serve.fleet import read_contract_line
    from gene2vec_tpu.serve.registry import l2_normalize
    from gene2vec_tpu.serve.tenancy import DEFAULT_BATCH_WEIGHT

    if smoke:
        vocab, dim, k, shards, chunk_rows = 4096, 32, 10, 2, 64
        rows_1m, dim_1m, queries_1m, clusters = 20000, 16, 64, 128
        oracle_q = 64
        mix_level, mix_duration = 50.0, 2.5
    else:
        vocab, dim = int(budget["rows_24k"]), int(budget["dim_24k"])
        k, shards = int(budget["k"]), int(budget["shards"])
        chunk_rows = int(budget["chunk_rows"])
        rows_1m, dim_1m = int(budget["rows_1m"]), int(budget["dim_1m"])
        queries_1m, clusters = int(budget["queries_1m"]), 1024
        oracle_q = 256
        # the mixed window measures batch INTERFERENCE, so it must run
        # at an operating point with headroom: at saturation (this
        # 2-shard scatter fleet collapses near ~80 rps at the full
        # 24k x 200 geometry on the CI container) any added work
        # explodes p99 by plain queueing, telling nothing about the
        # lane weight / leg sizing / pacing this phase gates
        mix_level, mix_duration = 25.0, 5.0

    result: dict = {"recipe": {
        "rows_24k": vocab, "dim_24k": dim, "k": k, "shards": shards,
        "chunk_rows": chunk_rows, "rows_1m": rows_1m,
        "dim_1m": dim_1m, "queries_1m": queries_1m,
        "batch_weight": DEFAULT_BATCH_WEIGHT,
    }}

    export_dir = os.path.join(tmp, "batch_export")
    jobs_dir = os.path.join(tmp, "batch_jobs")
    os.makedirs(jobs_dir, exist_ok=True)
    it = 1
    _write_iteration(export_dir, it, vocab_size=vocab, dim=dim)
    # _write_iteration derives the table from RandomState(iteration):
    # recompute it locally so the drill can referee the graph
    emb = np.random.RandomState(it).randn(vocab, dim).astype(np.float32)

    def spawn_fleet():
        argv = [
            sys.executable, "-m", "gene2vec_tpu.cli.fleet",
            "--export-dir", export_dir,
            "--shard-by-rows", str(shards),
            "--jobs-dir", jobs_dir,
            "--port", "0", "--health-interval", "0.25",
            "--unhealthy-after", "2", "--backoff-base", "0.3",
            "--swap-interval", "0.4", "--scrape-interval", "0.5",
            "--proxy-timeout-ms", "8000",
            "--shard-deadline-ms", "6000",
            "--seed", str(seed),
        ]
        p = subprocess.Popen(
            argv, stdout=subprocess.PIPE, text=True,
            env=chaos.child_env(), cwd=REPO,
        )
        return p, read_contract_line(p, 180.0)

    def hard_kill(p, contract):
        # SIGKILL the front door; its supervised replicas are orphaned,
        # not killed — reap them by contract pid so the restarted fleet
        # doesn't share the box with dead siblings' survivors
        p.kill()
        p.wait(timeout=30)
        for pid in contract.get("replica_pids", []):
            try:
                os.kill(int(pid), signal.SIGKILL)
            except (OSError, ValueError):
                pass

    log(f"spawning sharded fleet ({shards} shards, jobs dir "
        f"{jobs_dir}) over {vocab} x {dim}")
    proc, info = spawn_fleet()
    try:
        url = info["url"]
        assert info.get("jobs_dir") == jobs_dir
        doc = _batch_post_json(f"{url}/v1/jobs", {
            "type": "knn_graph", "k": k, "chunk_rows": chunk_rows,
            "job_id": "drill-graph-a",
        })
        assert doc.get("state") in ("pending", "running"), doc
        # let it commit real progress, then yank the plug mid-build
        kill_floor = max(2 * chunk_rows, int(vocab * 0.25))

        def mid_build():
            d = _http_json(f"{url}/v1/jobs/drill-graph-a", timeout=5.0)
            if d.get("state") in ("done", "failed", "cancelled"):
                raise AssertionError(
                    f"job reached {d['state']!r} before the drill "
                    "could SIGKILL it mid-build — geometry too small "
                    f"({d.get('records_done')} records)"
                )
            return int(d.get("records_done") or 0) >= kill_floor

        wait_until(mid_build, timeout_s=600.0, interval_s=0.05,
                   what="mid-build kill point")
        d = _http_json(f"{url}/v1/jobs/drill-graph-a", timeout=5.0)
        killed_at = int(d.get("records_done") or 0)
        assert killed_at < vocab, "job finished before the SIGKILL"
        log(f"SIGKILL at {killed_at}/{vocab} committed records")
    except BaseException:
        hard_kill(proc, info)
        raise
    hard_kill(proc, info)

    log("restarting the fleet on the same export + jobs dirs")
    proc, info = spawn_fleet()
    try:
        url = info["url"]

        def job_done(job_id):
            def check():
                d = _http_json(f"{url}/v1/jobs/{job_id}", timeout=5.0)
                if d.get("state") in ("failed", "cancelled"):
                    raise AssertionError(
                        f"{job_id} -> {d['state']}: {d.get('error')}"
                    )
                return d if d.get("state") == "done" else None
            return check

        def fetch(job_id, out_dir):
            from gene2vec_tpu.cli.batch import _fetch
            try:
                return _fetch(url, job_id, out_dir)
            except SystemExit as e:  # cli helper -> phase failure
                raise AssertionError(
                    f"artifact fetch for {job_id} failed: {e}"
                ) from e

        # the journaled "running" job resumes from its committed
        # cursor without being resubmitted — that IS the contract
        a = wait_until(job_done("drill-graph-a"), timeout_s=900.0,
                       interval_s=0.2, what="resumed graph job done")
        resumed = int(a["result"]["resumed_records"])
        assert 0 < resumed < vocab, (
            f"resumed_records={resumed}: the restarted fleet did not "
            "resume from committed progress"
        )
        dir_a = os.path.join(tmp, "batch_fetched_a")
        fetch("drill-graph-a", dir_a)

        # uninterrupted control through the SAME scatter path — the
        # bit-identity claim is about the pipeline, so the control
        # must share it (an in-process EngineBackend build could
        # legally differ in merge tie order)
        _batch_post_json(f"{url}/v1/jobs", {
            "type": "knn_graph", "k": k, "chunk_rows": chunk_rows,
            "job_id": "drill-graph-b",
        })
        b = wait_until(job_done("drill-graph-b"), timeout_s=900.0,
                       interval_s=0.2, what="control graph job done")
        dir_b = os.path.join(tmp, "batch_fetched_b")
        fetch("drill-graph-b", dir_b)

        pair = []
        for d_ in (dir_a, dir_b):
            with open(os.path.join(d_, DATA_NAME), "rb") as f:
                data_blob = f.read()
            with open(os.path.join(d_, TOKENS_NAME), "rb") as f:
                tok_blob = f.read()
            pair.append((data_blob, tok_blob))
        bit_exact = pair[0] == pair[1]

        tokens_g, ids, scores, meta = load_graph(dir_a)
        assert int(meta["iteration"]) == it
        assert ids.shape == (vocab, k), ids.shape
        assert tokens_g == [f"G{i}" for i in range(vocab)]
        q_rows = np.sort(np.random.RandomState(seed + 7).choice(
            vocab, size=oracle_q, replace=False
        ))
        want = _batch_oracle_topk(l2_normalize(emb), q_rows, k)
        hits = sum(
            len(set(ids[int(r)]) & set(want[i]))
            for i, r in enumerate(q_rows)
        )
        recall = hits / float(oracle_q * k)
        result["graph_24k"] = {
            "rows": vocab, "dim": dim, "k": k, "shards": shards,
            "chunk_rows": chunk_rows,
            "rows_per_sec": b["result"]["rows_per_sec"],
            "wall_s": b["result"]["wall_s"],
            "chunks": b["result"]["chunks"],
            "data_bytes": b["result"]["data_bytes"],
            "yielded_s": b["result"]["yielded_s"],
            "recall_at_10": round(recall, 4),
            "oracle_queries": oracle_q,
            "killed_at_records": killed_at,
            "resumed_records": resumed,
            "resume_bit_exact": bool(bit_exact),
        }
        log(f"graph: {json.dumps(result['graph_24k'])}")
        assert bit_exact, (
            "SIGKILLed-and-resumed graph artifact diverged from the "
            "uninterrupted control"
        )
        assert recall >= float(budget["min_recall_at_10"]), (
            f"graph recall@{k} {recall} < budget "
            f"{budget['min_recall_at_10']}"
        )

        # -- mixed workload: interactive p99 while a graph job runs in
        #    the background lane (scripts/serve_loadgen.py owns the
        #    measurement; the drill just points it at the live fleet)
        mix_out = os.path.join(tmp, "batch_loadgen_mixed.json")
        lg = [
            sys.executable,
            os.path.join(REPO, "scripts", "serve_loadgen.py"),
            "--url", url, "--mode", "open",
            "--levels", f"{mix_level:g}",
            "--duration", f"{mix_duration:g}",
            "--batch-phase", "--batch-k", str(k),
            "--batch-chunk-rows", str(chunk_rows),
            "--seed", str(seed), "--output", mix_out,
        ]
        log("mixed-workload phase: serve_loadgen --batch-phase "
            "against the live fleet")
        rc = subprocess.call(
            lg, stdout=subprocess.DEVNULL, env=chaos.child_env(),
            cwd=REPO,
        )
        assert rc == 0, f"serve_loadgen --batch-phase exited {rc}"
        with open(mix_out) as f:
            bm = json.load(f)["batch_mixed"]
        result["mixed"] = {
            "level": bm["level"],
            "interactive_p99_baseline_ms":
                bm["interactive_p99_baseline_ms"],
            "interactive_p99_under_batch_ms":
                bm["interactive_p99_under_batch_ms"],
            "p99_delta_ms": bm["p99_delta_ms"],
            "p99_delta_frac": bm["p99_delta_frac"],
            "batch_goodput_rows_per_sec":
                bm["batch"]["goodput_rows_per_sec"],
            "batch_state_after_window":
                bm["batch"]["state_after_window"],
        }
        log(f"mixed: {json.dumps(result['mixed'])}")
        frac, ms = (result["mixed"]["p99_delta_frac"],
                    result["mixed"]["p99_delta_ms"])
        assert (
            (frac is not None
             and frac <= float(budget["max_p99_delta_frac"]))
            or (ms is not None
                and ms <= float(budget["max_p99_delta_ms"]))
        ), (
            f"interactive p99 under batch load regressed by {frac} "
            f"({ms} ms) — outside both max_p99_delta_frac "
            f"{budget['max_p99_delta_frac']} and max_p99_delta_ms "
            f"{budget['max_p99_delta_ms']}"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    # -- 1M-row scaling table, in-process (the EngineBackend + IVF
    #    path cli.batch local mode uses; at this scale the fleet
    #    contributes nothing but HTTP overhead) ----------------------
    log(f"scale table: {rows_1m:,} x {dim_1m} IVF "
        f"(clusters={clusters})")
    import jax.numpy as jnp

    from gene2vec_tpu.batch.runner import EngineBackend
    from gene2vec_tpu.serve.ann import build_index
    from gene2vec_tpu.serve.engine import SimilarityEngine
    from gene2vec_tpu.serve.registry import LoadedModel

    unit_1m = _batch_clustered_unit(rows_1m, dim_1m, clusters, seed)
    ivf = build_index(unit_1m, "ivf", clusters=clusters, seed=seed)
    model_1m = LoadedModel(
        dim=dim_1m, iteration=1,
        tokens=tuple(map(str, range(rows_1m))),
        index={},  # knn_rows never consults the token index
        emb=unit_1m, unit=jnp.asarray(unit_1m),
        source="synthetic", meta={}, ann=ivf,
    )
    nprobe, rescore_mult = 32, 4
    backend = EngineBackend(model_1m, SimilarityEngine(
        max_batch=128, index="ivf", nprobe=nprobe,
        rescore_mult=rescore_mult,
    ))
    sub = 128
    start0 = int(np.random.RandomState(seed + 11).randint(
        0, rows_1m - queries_1m - sub
    ))
    backend.knn_rows(start0 + queries_1m, min(sub, queries_1m), k)  # jit warmup
    t0 = time.monotonic()
    parts = []
    done = 0
    while done < queries_1m:
        n = min(sub, queries_1m - done)
        ids_n, _ = backend.knn_rows(start0 + done, n, k)
        parts.append(ids_n)
        done += n
    wall = max(time.monotonic() - t0, 1e-9)
    ids_1m = np.concatenate(parts)
    want_1m = _batch_oracle_topk(
        unit_1m, np.arange(start0, start0 + queries_1m), k
    )
    hits = sum(
        len(set(ids_1m[i]) & set(want_1m[i]))
        for i in range(queries_1m)
    )
    recall_1m = hits / float(queries_1m * k)
    result["graph_1m"] = {
        "rows": rows_1m, "dim": dim_1m, "k": k,
        "queries": queries_1m, "index": "ivf",
        "clusters": int(ivf.n_clusters), "nprobe": nprobe,
        "rescore_mult": rescore_mult,
        "build_seconds": round(float(ivf.build_seconds), 3),
        "rows_per_sec": round(queries_1m / wall, 3),
        "recall_at_10": round(recall_1m, 4),
    }
    log(f"scale: {json.dumps(result['graph_1m'])}")
    assert recall_1m >= float(budget["min_recall_at_10_1m"]), (
        f"1M-row recall@{k} {recall_1m} < budget "
        f"{budget['min_recall_at_10_1m']}"
    )
    return result


# -- phase: multi-model catalog isolation -----------------------------------


def drill_catalog(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """The multi-model serving plane's isolation drill: a two-model
    catalog fleet (``cli.fleet --catalog``) under continuous verified
    load must survive (A) a hot swap of the DEFAULT model — only its
    pool flips iteration, the sibling's answers never move — and (B) a
    load ramp on the second model that scales ONLY that model's pool,
    while verified light load on the cold default model stays clean.
    Every verified answer is checked for WHICH model answered
    (``model.name`` + ``model.dim``): the gate is zero wrong, zero
    mixed-iteration, zero cross-model answers, availability >= the
    budget floor."""
    import threading

    from gene2vec_tpu.serve.fleet import read_contract_line

    vocab = int(budget.get("vocab", 48))
    dim_a = int(budget.get("dim_default", 8))
    dim_b = int(budget.get("dim_second", 16))
    k = int(budget.get("k", 4))
    max_replicas = int(budget.get("max_replicas", 2))
    scrape_s = float(budget.get("scrape_interval_s", 0.25))
    max_ticks = float(budget.get("max_scale_up_detection_ticks", 40))
    swap_window_s = 6.0 if smoke else 10.0
    ramp_workers = 48

    name_a = f"dim{dim_a}"   # the default model (gets the hot swap)
    name_b = f"dim{dim_b}"   # the second model (gets the load ramp)
    export_a = os.path.join(tmp, "catalog_export_a")
    export_b = os.path.join(tmp, "catalog_export_b")
    _write_iteration(export_a, 1, vocab_size=vocab, dim=dim_a)
    _write_iteration(export_b, 1, vocab_size=vocab, dim=dim_b)
    spec_path = os.path.join(tmp, "catalog_spec.json")
    with open(spec_path, "w") as f:
        json.dump({
            "schema": "gene2vec-tpu/catalog/v1",
            "default": name_a,
            "models": {
                name_a: {"export_dir": export_a, "dim": dim_a,
                         "replicas": 1},
                name_b: {"export_dir": export_b, "dim": dim_b,
                         "replicas": 1},
            },
        }, f)

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_a, "--catalog", spec_path,
        "--min-replicas", "1", "--max-replicas", str(max_replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3", "--proxy-timeout-ms", "4000",
        "--proxy-workers", "64",
        "--scrape-interval", str(scrape_s),
        "--alert-rules", "none",
        "--seed", str(seed),
        # scaler drill knobs (the drill_autoscale geometry: breach in 2
        # ticks, slow clear, short cooldown)
        "--scale-up-queue", "4", "--scale-up-rejection", "0.02",
        "--scale-up-after", "2", "--scale-down-after", "60",
        "--scale-down-queue", "3", "--scale-cooldown", "1.0",
        "--drain-timeout", "15",
        # replica geometry: saturable by a CPU drill (no LRU, tiny
        # batch, small bounded queue) + a fast swap watcher poll so
        # the hot-swap window fits the smoke budget
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--serve-arg=--max-delay-ms", "--serve-arg=100",
        "--serve-arg=--max-batch", "--serve-arg=4",
        "--serve-arg=--max-queue", "--serve-arg=8",
        "--serve-arg=--http-workers", "--serve-arg=32",
        "--serve-arg=--poll-interval", "--serve-arg=0.3",
    ]
    log(f"spawning catalog fleet: {name_a} (default) + {name_b}, "
        f"1 -> {max_replicas} replicas per pool")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    result: dict = {
        "recipe": {
            "models": 2, "replicas_per_model": 1,
            "max_replicas": max_replicas, "vocab": vocab,
            "dim_default": dim_a, "dim_second": dim_b, "k": k,
        },
        "models": [name_a, name_b],
        "default": name_a,
    }
    try:
        info = read_contract_line(proc, 240.0)
        url = info["url"]
        contract = info.get("catalog") or {}
        assert contract.get("default") == name_a, (
            f"contract line missing catalog facts: {info}"
        )
        log(f"catalog fleet front door at {url}; pools "
            f"{ {m: d['slots'] for m, d in contract['models'].items()} }")

        query_genes = [f"G{i}" for i in range(8)]

        def post(model: str, gene: str, timeout: float = 10.0):
            """(status, doc-or-None); the default model goes through
            the UNPREFIXED route — its backward-compat surface is part
            of what this drill verifies."""
            path = (
                "/v1/similar" if model == name_a
                else f"/v1/{model}/similar"
            )
            body = json.dumps({"genes": [gene], "k": k}).encode("utf-8")
            req = urllib.request.Request(
                url + path, data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return r.status, json.loads(r.read().decode("utf-8"))
            except urllib.error.HTTPError as e:
                e.read()
                e.close()
                return e.code, None
            except Exception:
                return 0, None

        def answer_key(doc: dict):
            m = doc["model"]
            return (
                m.get("name"), m.get("dim"), m["iteration"],
                tuple(n["gene"]
                      for n in doc["results"][0]["neighbors"]),
            )

        def reference_for(model: str) -> tuple:
            status, doc = post(model, "G0", timeout=15.0)
            assert status == 200, (
                f"reference query for {model} failed ({status})"
            )
            refs = {}
            for g in query_genes:
                status, doc = post(model, g, timeout=15.0)
                assert status == 200, (
                    f"reference query {model}/{g} failed ({status})"
                )
                refs[g] = answer_key(doc)
            return refs

        ref1 = {name_a: reference_for(name_a),
                name_b: reference_for(name_b)}
        for m, dim in ((name_a, dim_a), (name_b, dim_b)):
            for key in ref1[m].values():
                assert key[0] == m and key[1] == dim, (
                    f"reference answer for {m} came from "
                    f"{key[0]}/dim={key[1]} — catalog routing is broken"
                )

        # --- (A) hot-swap the DEFAULT model under verified load -------
        # every in-window answer is logged raw and classified POST-HOC:
        # iteration 1 answers must match the pre-swap reference,
        # iteration 2 answers the post-swap one (collected after the
        # swap settles); anything else is wrong/mixed.  The sibling
        # model must never leave iteration 1.
        window_log = {name_a: [], name_b: []}  # (status, key-or-None)
        stop = threading.Event()
        lock = threading.Lock()

        def verified_worker(model: str, widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while not stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, doc = post(model, g)
                with lock:
                    window_log[model].append(
                        (g, status,
                         answer_key(doc) if status == 200 else None)
                    )
                time.sleep(0.05)

        workers = [
            threading.Thread(
                target=verified_worker, args=(m, i), daemon=True,
            )
            for i, m in enumerate((name_a, name_a, name_b, name_b))
        ]
        for t in workers:
            t.start()
        time.sleep(swap_window_s / 3.0)
        _write_iteration(export_a, 2, vocab_size=vocab, dim=dim_a)
        t_swap = time.monotonic()
        log(f"staged iteration 2 for {name_a}; waiting for the swap")

        def swapped():
            status, doc = post(name_a, "G0")
            return (
                status == 200 and doc["model"]["iteration"] == 2
            ) or None

        wait_until(swapped, 120.0, interval_s=0.25,
                   what=f"{name_a} iteration 2 via the front door")
        swap_visible_s = time.monotonic() - t_swap
        time.sleep(swap_window_s / 3.0)
        stop.set()
        for t in workers:
            t.join(timeout=30.0)
        ref2_a = reference_for(name_a)
        assert all(key[2] == 2 for key in ref2_a.values()), (
            f"{name_a} post-swap reference still serves iteration 1"
        )
        result["swap"] = {
            "model": name_a, "from_iteration": 1, "to_iteration": 2,
            "visible_s": round(swap_visible_s, 2),
        }

        # --- (B) ramp the SECOND model; verify the cold default -------
        stop = threading.Event()

        def ramp_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 100 + widx)
            while not stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                post(name_b, g)

        def cold_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 900 + widx)
            while not stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, doc = post(name_a, g)
                with lock:
                    window_log[name_a].append(
                        (g, status,
                         answer_key(doc) if status == 200 else None)
                    )
                time.sleep(0.1)

        t_ramp = time.monotonic()
        ramp = [
            threading.Thread(target=ramp_worker, args=(w,), daemon=True)
            for w in range(ramp_workers)
        ] + [
            threading.Thread(target=cold_worker, args=(w,), daemon=True)
            for w in range(2)
        ]
        for t in ramp:
            t.start()

        def scale_up_decided():
            m = _fetch_metrics(url)
            return m.get("fleet_scale_up_total", 0.0) >= 1.0 or None

        wait_until(scale_up_decided, max_ticks * scrape_s + 10.0,
                   interval_s=0.1, what="per-model scale-up decision")
        detection_s = time.monotonic() - t_ramp
        detection_ticks = max(1, int(np.ceil(detection_s / scrape_s)))

        def pool_scaled():
            h = _http_json(url + "/healthz", timeout=10.0)
            models = h.get("models") or {}
            return (
                models.get(name_b, {}).get("up", 0) >= max_replicas
            ) or None

        wait_until(pool_scaled, 240.0, interval_s=0.5,
                   what=f"{name_b} pool at {max_replicas} replicas")
        scale_up_completed_s = time.monotonic() - t_ramp
        stop.set()
        for t in ramp:
            t.join(timeout=30.0)
        health = _http_json(url + "/healthz", timeout=10.0)
        cold_up = health["models"][name_a]["up"]
        hot_up = health["models"][name_b]["up"]
        assert cold_up == 1, (
            f"the ramp on {name_b} grew the COLD {name_a} pool to "
            f"{cold_up} — pool isolation is broken"
        )
        log(f"scale-up: {name_b} pool at {hot_up} "
            f"({scale_up_completed_s:.1f}s after the ramp), {name_a} "
            f"pool still {cold_up}")
        result["scale_up"] = {
            "model": name_b,
            "detection_ticks": detection_ticks,
            "completed_s": round(scale_up_completed_s, 1),
            "cold_pool_final": cold_up,
            "hot_pool_final": hot_up,
        }

        # --- post-hoc classification of every verified answer ---------
        counts = {"requests": 0, "ok": 0, "dropped": 0, "wrong": 0,
                  "mixed": 0, "cross_model": 0}
        bad_sample: list = []  # first few non-ok answers, for forensics
        expected = {
            name_a: {"dim": dim_a,
                     "refs": {1: ref1[name_a], 2: ref2_a}},
            name_b: {"dim": dim_b, "refs": {1: ref1[name_b]}},
        }
        for model, entries in window_log.items():
            want = expected[model]
            for g, status, key in entries:
                counts["requests"] += 1
                if status != 200 or key is None:
                    counts["dropped"] += 1
                    continue
                name, dim, it, neighbors = key
                if name != model or dim != want["dim"]:
                    counts["cross_model"] += 1
                    kind = "cross_model"
                elif it not in want["refs"]:
                    counts["mixed"] += 1
                    kind = "mixed"
                elif key != want["refs"][it][g]:
                    counts["wrong"] += 1
                    kind = "wrong"
                else:
                    counts["ok"] += 1
                    continue
                if len(bad_sample) < 6:
                    bad_sample.append({
                        "kind": kind, "model": model, "gene": g,
                        "got": list(key),
                        "want": list(want["refs"].get(it, {}).get(g, ())),
                    })
        availability = counts["ok"] / max(counts["requests"], 1)
        counts["availability"] = round(availability, 5)
        result["verified"] = counts
        if bad_sample:
            result["bad_sample"] = bad_sample
            log(f"bad answers (sample): {bad_sample}")
        log(f"verified {counts['requests']} answers: "
            f"{counts['ok']} ok, {counts['dropped']} dropped, "
            f"{counts['wrong']} wrong, {counts['mixed']} mixed, "
            f"{counts['cross_model']} cross-model "
            f"(availability {availability:.4f})")
        assert counts["cross_model"] <= int(
            budget.get("max_cross_model_answers", 0)
        ), f"{counts['cross_model']} answers crossed models"
        assert counts["wrong"] <= int(
            budget.get("max_wrong_answers", 0)
        ), f"{counts['wrong']} wrong answers"
        assert counts["mixed"] <= int(
            budget.get("max_mixed_answers", 0)
        ), f"{counts['mixed']} mixed-iteration answers"
        floor = float(budget.get("min_availability", 0.99))
        assert availability >= floor, (
            f"verified availability {availability:.4f} < {floor}"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    return result


PHASES = ("training_resume", "corruption", "serve", "async_overhead",
          "fleet", "alerts", "autoscale", "shard", "loop", "batch",
          "catalog")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_drill",
        description="fault-injection drill for the resilience subsystem",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized drill: fewer iterations per phase")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--fleet-out", default=None, metavar="PATH",
                    help="also write the fleet phase's results (plus "
                         "budget) as a standalone bench document, e.g. "
                         "BENCH_FLEET_r08.json — the record "
                         "analysis/passes_fleet.py gates on")
    ap.add_argument("--alerts-out", default=None, metavar="PATH",
                    help="also write the alerts phase's results (plus "
                         "budget) as a standalone bench document, e.g. "
                         "BENCH_ALERTS_r13.json — the record "
                         "analysis/passes_alerts.py gates on")
    ap.add_argument("--autoscale-out", default=None, metavar="PATH",
                    help="also write the autoscale phase's results "
                         "(plus budget) as a standalone bench document, "
                         "e.g. BENCH_AUTOSCALE_r14.json — the record "
                         "analysis/passes_autoscale.py gates on")
    ap.add_argument("--shard-out", default=None, metavar="PATH",
                    help="also write the shard phase's results (the "
                         "10M scatter-merge bench + HTTP drill) as a "
                         "standalone bench document, e.g. "
                         "BENCH_SHARD_r15.json — the record "
                         "analysis/passes_shard.py gates on (run "
                         "WITHOUT --smoke for the committed artifact; "
                         "a smoke run is off the pinned recipe)")
    ap.add_argument("--loop-out", default=None, metavar="PATH",
                    help="also write the loop phase's results (the "
                         "continuous-learning cycle: ingest -> warm "
                         "start -> quality gate -> shadow -> promote "
                         "with a SIGKILL in every state) as a "
                         "standalone bench document, e.g. "
                         "BENCH_LOOP_r16.json — the record "
                         "analysis/passes_loop.py gates on")
    ap.add_argument("--batch-out", default=None, metavar="PATH",
                    help="also write the batch phase's results (the "
                         "kNN-graph SIGKILL-resume drill through "
                         "/v1/jobs + the 1M IVF scaling table + the "
                         "mixed-workload p99 delta) as a standalone "
                         "bench document, e.g. BENCH_BATCH_r19.json — "
                         "the record analysis/passes_batch.py gates "
                         "on (run WITHOUT --smoke for the committed "
                         "artifact; a smoke run is off the pinned "
                         "recipe)")
    ap.add_argument("--catalog-out", default=None, metavar="PATH",
                    help="also write the catalog phase's results (the "
                         "two-model isolation drill: hot-swap the "
                         "default model under verified load on both "
                         "models, then ramp the second model and prove "
                         "only its pool scales) as a standalone bench "
                         "document, e.g. BENCH_CATALOG_r20.json — the "
                         "record analysis/passes_catalog.py gates on")
    ap.add_argument("--only", default=None,
                    help=f"comma-separated phases from {PHASES}")
    ap.add_argument("--seed", type=int, default=None,
                    help="kill-point seed (default: derived from time)")
    ap.add_argument("--tmp", default=None, help="work dir (default: mkdtemp)")
    args = ap.parse_args(argv)

    only = args.only.split(",") if args.only else list(PHASES)
    unknown = [p for p in only if p not in PHASES]
    if unknown:
        ap.error(f"unknown phase(s) {unknown}; choose from {PHASES}")

    # the async_overhead phase trains IN-PROCESS: pin the CPU backend
    # before jax initializes, exactly like chaos.child_env does for the
    # child phases — the session env may point at a real accelerator,
    # and the overhead budget's reference numbers are CPU-derived
    os.environ["JAX_PLATFORMS"] = "cpu"

    import tempfile

    from gene2vec_tpu.analysis.passes_hlo import load_budgets

    tmp = args.tmp or tempfile.mkdtemp(prefix="chaos_drill_")
    seed = args.seed if args.seed is not None else int(time.time()) % 100000
    budgets = load_budgets()
    budget = budgets["resilience"]["async_ckpt"]
    fleet_budget = budgets["fleet"]["chaos"]
    alerts_budget = budgets["alerts"]["detection"]
    autoscale_budget = budgets["autoscale"]["elasticity"]
    shard_budget = budgets["shard"]["scatter"]
    loop_budget = budgets["loop"]["promotion"]
    batch_budget = budgets["batch"]["graph"]
    catalog_budget = budgets["catalog"]["isolation"]
    iters = 3 if args.smoke else 5

    doc = {
        "schema": "gene2vec-tpu/chaos-drill/v1",
        # provenance stamp (ledger contract, docs/BENCHMARKS.md)
        "schema_version": 1,
        "command": " ".join([sys.executable, *sys.argv]),
        "created_unix": time.time(),
        "host": socket.gethostname(),
        "smoke": bool(args.smoke),
        "seed": seed,
        "phases": {},
        "passed": False,
    }
    t0 = time.monotonic()
    failed = None
    for phase in only:
        log(f"=== phase: {phase} ===")
        try:
            if phase == "training_resume":
                doc["phases"][phase] = drill_training_resume(tmp, iters, seed)
            elif phase == "corruption":
                doc["phases"][phase] = drill_corruption(tmp)
            elif phase == "serve":
                doc["phases"][phase] = drill_serve(tmp)
            elif phase == "async_overhead":
                doc["phases"][phase] = drill_async_overhead(tmp, budget)
            elif phase == "fleet":
                doc["phases"][phase] = drill_fleet(
                    tmp, args.smoke, fleet_budget, seed
                )
            elif phase == "alerts":
                doc["phases"][phase] = drill_alerts(
                    tmp, args.smoke, alerts_budget, seed
                )
            elif phase == "autoscale":
                doc["phases"][phase] = drill_autoscale(
                    tmp, args.smoke, autoscale_budget, seed
                )
            elif phase == "shard":
                doc["phases"][phase] = drill_shard(
                    tmp, args.smoke, shard_budget, seed
                )
            elif phase == "loop":
                doc["phases"][phase] = drill_loop(
                    tmp, args.smoke, loop_budget, seed
                )
            elif phase == "batch":
                doc["phases"][phase] = drill_batch(
                    tmp, args.smoke, batch_budget, seed
                )
            elif phase == "catalog":
                doc["phases"][phase] = drill_catalog(
                    tmp, args.smoke, catalog_budget, seed
                )
        except Exception as e:
            failed = f"{phase}: {e}"
            doc["phases"][phase] = {"error": str(e)}
            log(f"PHASE FAILED — {e}")
            break
    doc["wall_seconds"] = round(time.monotonic() - t0, 2)
    doc["passed"] = failed is None
    if failed:
        doc["failed"] = failed

    blob = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
        log(f"wrote {args.out}")
    if args.fleet_out and "fleet" in doc["phases"]:
        fleet_doc = {
            "schema": "gene2vec-tpu/bench-fleet/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "fleet_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["fleet"],
            "fleet": doc["phases"]["fleet"],
        }
        with open(args.fleet_out, "w") as f:
            f.write(json.dumps(fleet_doc, indent=1) + "\n")
        log(f"wrote {args.fleet_out}")
    if args.alerts_out and "alerts" in doc["phases"]:
        alerts_doc = {
            "schema": "gene2vec-tpu/bench-alerts/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "alerts_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["alerts"],
            "alerts": doc["phases"]["alerts"],
        }
        with open(args.alerts_out, "w") as f:
            f.write(json.dumps(alerts_doc, indent=1) + "\n")
        log(f"wrote {args.alerts_out}")
    if args.autoscale_out and "autoscale" in doc["phases"]:
        autoscale_doc = {
            "schema": "gene2vec-tpu/bench-autoscale/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "autoscale_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["autoscale"],
            "autoscale": doc["phases"]["autoscale"],
        }
        with open(args.autoscale_out, "w") as f:
            f.write(json.dumps(autoscale_doc, indent=1) + "\n")
        log(f"wrote {args.autoscale_out}")
    if args.loop_out and "loop" in doc["phases"]:
        loop_doc = {
            "schema": "gene2vec-tpu/bench-loop/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "loop_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["loop"],
            "loop": doc["phases"]["loop"],
        }
        with open(args.loop_out, "w") as f:
            f.write(json.dumps(loop_doc, indent=1) + "\n")
        log(f"wrote {args.loop_out}")
    if args.batch_out and "batch" in doc["phases"]:
        batch_doc = {
            "schema": "gene2vec-tpu/bench-batch/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "batch_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["batch"],
            "batch": doc["phases"]["batch"],
        }
        with open(args.batch_out, "w") as f:
            f.write(json.dumps(batch_doc, indent=1) + "\n")
        log(f"wrote {args.batch_out}")
    if args.catalog_out and "catalog" in doc["phases"]:
        catalog_doc = {
            "schema": "gene2vec-tpu/bench-catalog/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "catalog_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["catalog"],
            "catalog": doc["phases"]["catalog"],
        }
        with open(args.catalog_out, "w") as f:
            f.write(json.dumps(catalog_doc, indent=1) + "\n")
        log(f"wrote {args.catalog_out}")
    if args.shard_out and "shard" in doc["phases"]:
        shard_doc = {
            "schema": "gene2vec-tpu/bench-shard/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "shard_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["shard"],
            "shard": doc["phases"]["shard"],
        }
        with open(args.shard_out, "w") as f:
            f.write(json.dumps(shard_doc, indent=1) + "\n")
        log(f"wrote {args.shard_out}")
    print(blob)
    log("DRILL PASSED" if doc["passed"] else "DRILL FAILED")
    return 0 if doc["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
