#!/usr/bin/env python
"""Chaos drill: rehearse the failure model against the real CLIs.

Five phases (docs/RESILIENCE.md runbook):

* **training_resume** — run the real training CLI to completion as the
  reference, then SIGKILL a second run at a random ``iteration N done``
  line (mid-checkpoint territory) and rerun it; the resumed run's final
  embedding must be BIT-exact against the uninterrupted one.  A third
  run takes SIGTERM instead and must drain: exit ``EXIT_PREEMPTED``,
  stamp ``interrupted=true`` in its run manifest, and also resume
  bit-exact.
* **corruption** — truncate the newest checkpoint npz / corrupt a
  manifest CRC and assert verified discovery falls back to the previous
  iteration instead of surfacing the torn one.
* **serve** — spawn the real serve CLI over a live export dir: a good
  newer checkpoint hot-swaps in; a TORN newer checkpoint is never
  swapped (the watcher keeps serving the last good iteration); deleting
  the torn files mid-poll doesn't disturb the watcher; a subsequent
  good checkpoint swaps normally.
* **async_overhead** — train at the geometry pinned in
  ``analysis/budgets.json`` (section ``resilience``) with
  ``async_checkpoint`` on and assert the train loop's checkpoint span
  costs less than ``max_overhead_fraction`` of iteration wall time.
* **fleet** — spawn the real ``cli.fleet`` (3 supervised replicas + the
  resilient front-door proxy) over a live export, run closed-loop load
  through a :class:`~gene2vec_tpu.serve.client.ResilientClient` while
  one replica is SIGKILLed mid-run and another serves with injected
  HTTP faults (``resilience/faults.py``: latency, 503 substitution,
  connection resets, blackholes); assert client-observed availability
  >= the ``fleet`` budget, ZERO answers that are wrong or mix model
  iterations, and retry amplification within the retry budget.  Results
  are stamped into ``BENCH_FLEET_r08.json`` via ``--fleet-out`` and
  re-gated on every ``cli.analyze`` run
  (``analysis/passes_fleet.py``).
* **alerts** — the detection loop (docs/OBSERVABILITY.md#alerting):
  spawn ``cli.fleet`` with the default SLO alert rules, prove a CLEAN
  warmup fires nothing, then load a route where one byzantine replica
  injects deterministic 404s + latency and measure how long until the
  availability burn-rate rule fires in ``alerts.jsonl``; the
  auto-assembled incident bundle must CRC-verify via ``cli.obs
  incident`` and contain a reassembled trace through the faulty
  replica.  Stamped into ``BENCH_ALERTS_r13.json`` via ``--alerts-out``
  and gated by ``analysis/passes_alerts.py`` (budgets.json ``alerts``).
* **autoscale** — the elastic fleet (docs/SERVING.md#elastic-fleet):
  spawn ``cli.fleet --max-replicas`` and prove a load ramp produces a
  scale-up DECISION within the budgeted scrape ticks; ramp down and
  prove the hysteresis scale-down drains the victim with ZERO
  dropped/wrong/mixed answers under continuous verified load, plus a
  steady-state window with ZERO further actions (no flapping); then a
  per-tenant-quota fleet must hold a paced victim tenant at >= 0.99
  availability while an abusive tenant floods (tenant-labeled 429s).
  Stamped into ``BENCH_AUTOSCALE_r14.json`` via ``--autoscale-out``
  and gated by ``analysis/passes_autoscale.py`` (budgets.json
  ``autoscale``).

Exactly ONE JSON document goes to stdout (the machine contract);
progress chatter goes to stderr.  Exit 0 iff every phase passed.

Usage::

    python scripts/chaos_drill.py                 # full drill
    python scripts/chaos_drill.py --smoke         # CI-sized (~2 min)
    python scripts/chaos_drill.py --out BENCH_RESILIENCE_r07.json
    python scripts/chaos_drill.py --only fleet --fleet-out BENCH_FLEET_r08.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gene2vec_tpu.resilience import chaos  # noqa: E402
from gene2vec_tpu.resilience.preempt import EXIT_PREEMPTED  # noqa: E402


def log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def make_corpus(dirpath: str, vocab: int = 30, lines: int = 400,
                seed: int = 7) -> None:
    rng = np.random.RandomState(seed)
    os.makedirs(dirpath, exist_ok=True)
    rows = []
    for _ in range(lines):
        c = rng.randint(3)
        a, b = rng.choice(vocab // 3, 2, replace=False) + (vocab // 3) * c
        rows.append(f"G{a} G{b}")
    with open(os.path.join(dirpath, "pairs.txt"), "w") as f:
        f.write("\n".join(rows) + "\n")


def wait_until(fn, timeout_s: float, interval_s: float = 0.1,
               what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval_s)
    raise TimeoutError(f"{what} not reached within {timeout_s}s")


# -- phase: training resume-equivalence -------------------------------------


def drill_training_resume(tmp: str, iters: int, seed: int) -> dict:
    from gene2vec_tpu.io import checkpoint as ckpt

    data = os.path.join(tmp, "corpus")
    make_corpus(data)
    flags = dict(dim=8, iters=iters, batch_pairs=64, seed=3)

    log("training reference run (uninterrupted)")
    ref_dir = os.path.join(tmp, "train_ref")
    r = chaos.run_cli(chaos.gene2vec_argv(data, ref_dir, **flags))
    assert r.returncode == 0, f"reference run failed:\n{r.output[-2000:]}"
    ref = chaos.load_table(ref_dir, 8, iters)

    kill_at = int(np.random.RandomState(seed).randint(1, iters))
    log(f"SIGKILL run at 'iteration {kill_at} done'")
    kill_dir = os.path.join(tmp, "train_kill")
    r = chaos.run_cli_kill_on(
        chaos.gene2vec_argv(data, kill_dir, **flags),
        rf"iteration {kill_at} done", sig=signal.SIGKILL,
    )
    assert r.returncode != 0, "SIGKILLed child reported success"
    survivor = ckpt.latest_iteration(kill_dir, 8)
    assert survivor <= kill_at, (
        f"latest verified iteration {survivor} > kill point {kill_at}"
    )
    log(f"killed after iteration {kill_at}; verified survivor: {survivor}; "
        "resuming")
    r = chaos.run_cli(chaos.gene2vec_argv(data, kill_dir, **flags))
    assert r.returncode == 0, f"resume failed:\n{r.output[-2000:]}"
    resumed = chaos.load_table(kill_dir, 8, iters)
    kill_exact = bool(np.array_equal(ref, resumed))
    assert kill_exact, "SIGKILL resume diverged from the uninterrupted run"

    log("SIGTERM drain run at 'iteration 1 done'")
    term_dir = os.path.join(tmp, "train_term")
    r = chaos.run_cli_kill_on(
        chaos.gene2vec_argv(data, term_dir, **flags),
        r"iteration 1 done", sig=signal.SIGTERM,
    )
    assert r.returncode == EXIT_PREEMPTED, (
        f"SIGTERM drain exited {r.returncode}, expected {EXIT_PREEMPTED}:\n"
        f"{r.output[-2000:]}"
    )
    with open(os.path.join(term_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest.get("interrupted") is True, "manifest not stamped"
    r = chaos.run_cli(chaos.gene2vec_argv(data, term_dir, **flags))
    assert r.returncode == 0, f"post-drain resume failed:\n{r.output[-2000:]}"
    term_exact = bool(np.array_equal(ref, chaos.load_table(term_dir, 8, iters)))
    assert term_exact, "SIGTERM resume diverged from the uninterrupted run"
    return {
        "iters": iters,
        "sigkill_at_iteration": kill_at,
        "verified_survivor_iteration": survivor,
        "sigkill_resume_bit_exact": kill_exact,
        "sigterm_exit_code": EXIT_PREEMPTED,
        "sigterm_manifest_interrupted": True,
        "sigterm_resume_bit_exact": term_exact,
    }


# -- phase: corruption detection --------------------------------------------


def drill_corruption(tmp: str) -> dict:
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.resilience import snapshot as snap
    from gene2vec_tpu.sgns.model import SGNSParams

    d = os.path.join(tmp, "corrupt")
    vocab = Vocab([f"G{i}" for i in range(16)], np.arange(1, 17))
    for it in (1, 2, 3):
        params = SGNSParams(
            emb=np.full((16, 4), it, np.float32),
            ctx=np.zeros((16, 4), np.float32),
        )
        ckpt.save_iteration(d, 4, it, params, vocab)

    chaos.truncate_file(os.path.join(d, "gene2vec_dim_4_iter_3.npz"))
    snap.clear_verify_cache()
    after_truncate = ckpt.latest_iteration(d, 4)
    assert after_truncate == 2, (
        f"truncated newest not skipped: latest={after_truncate}"
    )

    chaos.corrupt_manifest_crc(os.path.join(d, "gene2vec_dim_4_iter_2"))
    snap.clear_verify_cache()
    after_crc = ckpt.latest_iteration(d, 4)
    assert after_crc == 1, f"stale CRC not skipped: latest={after_crc}"
    log("corruption: truncation and CRC rot both fall back")
    return {
        "truncated_newest_falls_back_to": after_truncate,
        "corrupt_crc_falls_back_to": after_crc,
    }


# -- phase: serve no-garbage-swap -------------------------------------------


def _http_json(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _write_iteration(export_dir: str, it: int, vocab_size: int = 16,
                     dim: int = 4) -> str:
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(it)
    vocab = Vocab([f"G{i}" for i in range(vocab_size)],
                  np.arange(1, vocab_size + 1))
    params = SGNSParams(
        emb=rng.randn(vocab_size, dim).astype(np.float32),
        ctx=np.zeros((vocab_size, dim), np.float32),
    )
    ckpt.save_iteration(export_dir, dim, it, params, vocab)
    return os.path.join(export_dir, f"gene2vec_dim_{dim}_iter_{it}")


def drill_serve(tmp: str) -> dict:
    export_dir = os.path.join(tmp, "serve_export")
    _write_iteration(export_dir, 1)

    # stderr inherits (serve chatter joins the drill's own stderr) so a
    # startup failure is visible, not swallowed into /dev/null
    proc = subprocess.Popen(
        [sys.executable, "-m", "gene2vec_tpu.cli.serve",
         "--export-dir", export_dir, "--port", "0",
         "--poll-interval", "0.3"],
        stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
    )
    try:
        # the contract line is read with a deadline — a serve CLI that
        # hangs before printing it must fail the drill, not wedge it
        # (serve/fleet.py read_contract_line is this exact lesson,
        # extracted; the fleet supervisor and this drill share it)
        from gene2vec_tpu.serve.fleet import read_contract_line

        info = read_contract_line(proc, 120.0)
        url = info["url"]
        log(f"serve CLI up at {url} (iteration {info['iteration']})")

        def iteration() -> int:
            return _http_json(url + "/healthz")["model"]["iteration"]

        assert iteration() == 1

        _write_iteration(export_dir, 2)
        wait_until(lambda: iteration() == 2, 15.0, what="hot swap to iter 2")
        log("good checkpoint hot-swapped")

        # torn newer checkpoint: staged in a side dir, truncated THERE,
        # then moved in (npz first, manifest last) — the watched dir
        # never holds a valid iteration 3 for even a poll cycle, so the
        # only way it can swap in is a verification bug
        stage = os.path.join(tmp, "serve_stage")
        prefix3 = _write_iteration(stage, 3)
        chaos.truncate_file(prefix3 + ".npz")
        base3 = os.path.basename(prefix3)
        for suffix in (".npz", ".txt", "_w2v.txt", ".MANIFEST.json"):
            os.replace(
                prefix3 + suffix, os.path.join(export_dir, base3 + suffix)
            )
        time.sleep(1.5)  # several poll cycles
        assert iteration() == 2, "torn checkpoint was hot-swapped!"
        log("torn checkpoint never swapped in")

        # delete the torn files mid-poll; the watcher must shrug
        chaos.delete_iteration(export_dir, 4, 3)
        time.sleep(0.8)
        assert iteration() == 2

        _write_iteration(export_dir, 4)
        wait_until(lambda: iteration() == 4, 15.0, what="hot swap to iter 4")
        log("recovered with the next good checkpoint")
        health = _http_json(url + "/healthz")
        assert health["status"] == "ok"
        return {
            "hot_swap_good": True,
            "torn_newest_never_swapped": True,
            "delete_mid_poll_survived": True,
            "final_iteration": 4,
        }
    finally:
        proc.kill()
        proc.wait(timeout=30)


# -- phase: fleet survives replica death + injected faults -------------------


def _parse_prom_counters(text: str) -> dict:
    """name -> value for the plain counter/gauge lines of a Prometheus
    text exposition (enough to read the fleet client's retry tallies)."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def _parse_fleet_view(url: str) -> dict:
    """``/metrics/fleet`` → {(name, labels): value} via the escape-aware
    parser the aggregator itself uses."""
    from gene2vec_tpu.obs.aggregate import parse_prometheus

    text = (
        urllib.request.urlopen(url + "/metrics/fleet", timeout=10.0)
        .read().decode("utf-8")
    )
    return {(s.name, s.labels): s.value for s in parse_prometheus(text)}


def _trace_tree_facts(doc: dict) -> "tuple":
    """(node name set, client_attempt count) over the reassembled tree
    including process-local compute subtrees."""
    names = set()
    attempts = 0

    def walk(node: dict) -> None:
        nonlocal attempts
        if node.get("name"):
            names.add(node["name"])
            if node["name"] == "client_attempt":
                attempts += 1
        for sub in node.get("process_spans", []):
            walk(sub)
        for child in node.get("children", []):
            walk(child)

    for root in doc.get("roots", []):
        walk(root)
    return names, attempts


def _find_cross_process_trace(export_dir: str, candidates) -> "tuple":
    """First candidate trace id whose reassembled tree spans the whole
    pipeline (proxy → ≥2 client attempts, i.e. a retried/failed-over
    request → replica → batcher → engine)."""
    from gene2vec_tpu.obs import flight as flight_mod

    for tid in candidates:
        doc = flight_mod.collect_trace(export_dir, tid)
        names, n_attempts = _trace_tree_facts(doc)
        if (
            {"proxy_request", "serve_request", "batch_item",
             "engine_topk"} <= names
            and n_attempts >= 2
        ):
            return tid, names, n_attempts
    return None, set(), 0


def drill_fleet(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    import threading

    from gene2vec_tpu.resilience.faults import FaultSpec
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "fleet_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    replicas = int(budget.get("replicas", 3))
    duration_s = 8.0 if smoke else 20.0
    workers = 4
    # the faulty replica: enough injected trouble to matter, spread over
    # every fault class the injector has; deterministic per drill seed
    faults = FaultSpec(
        seed=seed,
        latency_p=0.25, latency_ms=80.0,
        error_p=0.15, error_status=503,
        reset_p=0.05,
        blackhole_p=0.03, blackhole_hold_s=1.5,
    )
    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", str(replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3", "--proxy-timeout-ms", "4000",
        "--scrape-interval", "0.5",
        "--seed", str(seed),
        # no LRU on the replicas: the drill's 8-gene keyspace would be
        # fully cached after warmup, and a cached answer never touches
        # the batcher/engine — the cross-process trace this phase must
        # reassemble (and the availability gate should cover the whole
        # pipeline, not the cache)
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--replica-arg", "1:--faults", "--replica-arg",
        f"1:{faults.to_json()}",
    ]
    log(f"spawning fleet: {replicas} replicas, faults on replica 1")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        log(f"fleet front door at {url}; replica pids "
            f"{info['replica_pids']}")

        # every drill request is a SAMPLED trace root: the proxy and
        # replicas honor the propagated context, so cross-process
        # reassembly below has the full span pipeline to work with
        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=3, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )
        # pre-chaos reference answers: every response during chaos must
        # match one of these EXACTLY (same neighbors, same iteration) —
        # "zero wrong or cross-iteration answers" is checked per request
        query_genes = [f"G{i}" for i in range(8)]
        reference = {}
        for g in query_genes:
            r = client.request(
                "/v1/similar", {"genes": [g], "k": 4}, timeout_s=10.0
            )
            assert r.ok, f"reference query failed: {r.error_class}"
            reference[g] = (
                r.doc["model"]["iteration"],
                tuple(n["gene"] for n in r.doc["results"][0]["neighbors"]),
            )

        # fleet-view snapshot BEFORE the load window: the availability/
        # rejection numbers /metrics/fleet reports during chaos must be
        # reconcilable with the drill's own counts by delta math
        def _settled_view() -> dict:
            last = None
            for _ in range(30):
                view = _parse_fleet_view(url)
                key = (view.get(("fleet_responses", ())),
                       view.get(("fleet_requests", ())))
                if last is not None and key == last:
                    return view
                last = key
                time.sleep(0.6)
            return view

        pre_view = _settled_view()

        counts = {"ok": 0, "failed": 0, "wrong": 0, "mixed": 0,
                  "attempts": 0, "retries": 0, "rejected": 0}
        ok_latencies = []
        trace_log = []  # (monotonic, trace_id, retries, ok)
        lock = threading.Lock()
        stop_at = time.monotonic() + duration_s

        def worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                r = client.request(
                    "/v1/similar", {"genes": [g], "k": 4}, timeout_s=6.0
                )
                with lock:
                    counts["attempts"] += r.attempts
                    counts["retries"] += r.retries
                    trace_log.append(
                        (time.monotonic(), r.trace_id, r.retries, r.ok)
                    )
                    if r.error_class == "http_429":
                        counts["rejected"] += 1
                    if not r.ok:
                        counts["failed"] += 1
                        continue
                    ok_latencies.append(r.latency_s)
                    it = r.doc["model"]["iteration"]
                    got = tuple(
                        n["gene"]
                        for n in r.doc["results"][0]["neighbors"]
                    )
                    ref_it, ref_neighbors = reference[g]
                    if it != ref_it:
                        counts["mixed"] += 1
                    elif got != ref_neighbors:
                        counts["wrong"] += 1
                    else:
                        counts["ok"] += 1

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()

        # one third in: SIGKILL a healthy replica (index 0; index 1 is
        # the fault-injected one and stays up, misbehaving)
        time.sleep(duration_s / 3.0)
        victim = info["replica_pids"][0]
        log(f"SIGKILL replica 0 (pid {victim}) mid-load")
        os.kill(victim, signal.SIGKILL)
        t_kill = time.monotonic()

        for t in threads:
            t.join(timeout=duration_s + 30.0)

        total = (counts["ok"] + counts["failed"] + counts["wrong"]
                 + counts["mixed"])
        availability = counts["ok"] / max(total, 1)
        # replica-level attempts = front-door requests + its internal
        # retries/hedges (from the fleet /metrics registry); drill-level
        # attempts already count our own client's retry fan-out
        prom = _parse_prom_counters(
            urllib.request.urlopen(url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        proxy_retries = prom.get("fleet_client_retries_total", 0.0)
        proxy_hedges = prom.get("fleet_client_hedges_total", 0.0)
        amplification = (
            (counts["attempts"] + proxy_retries + proxy_hedges)
            / max(total, 1)
        )

        # --- the fleet SLO plane must agree with what we measured ----
        # traffic has stopped, so the aggregator's counters converge;
        # compare by DELTA across the load window.  Per-exchange vs
        # per-logical-request bookkeeping: the proxy counts one
        # response per drill-client ATTEMPT, and only a terminal
        # attempt can be 2xx, so ok≈(ok+wrong+mixed), total≈attempts.
        post_view = _settled_view()

        def _delta(name: str) -> float:
            return (post_view.get((name, ()), 0.0)
                    - pre_view.get((name, ()), 0.0))

        resp_delta = _delta("fleet_responses")
        ok_delta = _delta("fleet_ok")
        fleet_availability = ok_delta / max(resp_delta, 1.0)
        measured_attempt_av = (
            (counts["ok"] + counts["wrong"] + counts["mixed"])
            / max(counts["attempts"], 1)
        )
        fleet_rejection_rate = post_view.get(
            ("fleet_rejection_rate", ()), 0.0
        )
        measured_rejection_rate = counts["rejected"] / max(total, 1)
        fleet_queue_depth = post_view.get(("fleet_queue_depth", ()))
        route_labels = (("route", "/v1/similar"),)
        fleet_p50 = post_view.get(
            ("fleet_route_p50_seconds", route_labels)
        )
        fleet_p99 = post_view.get(
            ("fleet_route_p99_seconds", route_labels)
        )
        ok_latencies.sort()
        drill_p99 = (
            ok_latencies[min(len(ok_latencies) - 1,
                             int(0.99 * len(ok_latencies)))]
            if ok_latencies else None
        )
        log(
            f"fleet view: availability {fleet_availability:.4f} "
            f"(drill attempt-level {measured_attempt_av:.4f}), "
            f"/v1/similar p50/p99 {fleet_p50}/{fleet_p99}s "
            f"(drill client p99 {drill_p99}), queue depth "
            f"{fleet_queue_depth}, rejection {fleet_rejection_rate:.4f}"
        )
        assert resp_delta > 0, "/metrics/fleet saw none of the load"
        assert abs(fleet_availability - measured_attempt_av) <= 0.05, (
            f"/metrics/fleet availability {fleet_availability:.4f} "
            f"disagrees with the drill's measured "
            f"{measured_attempt_av:.4f}"
        )
        assert abs(
            fleet_rejection_rate - measured_rejection_rate
        ) <= 0.05, (
            f"/metrics/fleet rejection rate {fleet_rejection_rate:.4f} "
            f"disagrees with measured {measured_rejection_rate:.4f}"
        )
        assert fleet_queue_depth is not None and fleet_queue_depth >= 0, (
            "fleet_queue_depth missing from /metrics/fleet"
        )
        assert fleet_p50 is not None and fleet_p99 is not None, (
            "per-route p50/p99 missing from /metrics/fleet"
        )
        # replica-side handle time must sit below the client-observed
        # tail (which adds proxy+retries); bucket edges round UP <= 2x
        assert drill_p99 is None or fleet_p99 <= max(
            4.0 * drill_p99, 1.0
        ), (
            f"fleet p99 {fleet_p99}s implausible vs drill-observed "
            f"{drill_p99}s"
        )

        # --- cross-process trace reassembly for a SIGKILL-affected
        # request: an ok answer shortly after the kill whose tree shows
        # the proxy failing over (>= 2 client attempts) down to the
        # engine.  Reassembled in-process to pick a candidate, then
        # re-rendered through the real CLI (the operator's tool).
        time.sleep(1.0)  # let the last events.jsonl appends land
        candidates = [
            tid for (ts, tid, _retries, ok) in trace_log
            if ok and tid and ts >= t_kill
        ][:40]
        trace_id, names, n_attempts = _find_cross_process_trace(
            export_dir, candidates
        )
        assert trace_id is not None, (
            f"no post-SIGKILL request reassembled into a full "
            f"proxy→attempts→replica→batcher→engine trace "
            f"({len(candidates)} candidates tried)"
        )
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "trace",
             export_dir, trace_id],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0, (
            f"cli.obs trace failed (rc={cli.returncode}):\n{cli.stderr}"
        )
        for needle in ("proxy_request", "client_attempt",
                       "serve_request", "batch_item", "engine_topk"):
            assert needle in cli.stdout, (
                f"cli.obs trace output missing {needle!r}:\n{cli.stdout}"
            )
        log(
            f"trace {trace_id} reassembled end-to-end via cli.obs "
            f"trace ({n_attempts} client attempts, hops: "
            f"{sorted(names)})"
        )
        # the respawn is a fresh jax import — under the load the drill
        # itself just generated it can outlast the measurement window,
        # so WAIT for supervision to land rather than asserting on a
        # race (the availability numbers above are already final)
        def _restarts() -> int:
            health = _http_json(url + "/healthz", timeout=10.0)
            return sum(r["restarts"] for r in health["replicas"])

        try:
            restarts = wait_until(
                lambda: _restarts() or None, 90.0, interval_s=0.5,
                what="supervisor restarting the SIGKILLed replica",
            )
        except TimeoutError:
            restarts = 0
        result = {
            "replicas": replicas,
            "duration_s": duration_s,
            "workers": workers,
            "requests": total,
            "ok": counts["ok"],
            "failed": counts["failed"],
            "wrong_answers": counts["wrong"],
            "mixed_iteration_answers": counts["mixed"],
            "availability": round(availability, 5),
            "drill_client_retries": counts["retries"],
            "proxy_retries": int(proxy_retries),
            "retry_amplification": round(amplification, 4),
            "replica_restarts": restarts,
            "fleet_view_availability": round(fleet_availability, 5),
            "fleet_view_matches_measured": True,
            "fleet_route_p50_s": fleet_p50,
            "fleet_route_p99_s": fleet_p99,
            "fleet_queue_depth": fleet_queue_depth,
            "fleet_rejection_rate": round(fleet_rejection_rate, 5),
            "reassembled_trace_id": trace_id,
            "reassembled_trace_client_attempts": n_attempts,
            "faults_spec": faults.to_json(),
            "sigkilled_replica": 0,
            "budget": {k: v for k, v in budget.items()
                       if not k.startswith("_")},
        }
        log(f"fleet: availability {availability:.4f} over {total} "
            f"requests ({counts['failed']} failed), amplification "
            f"{amplification:.3f}, {restarts} restart(s)")
        assert total >= workers * duration_s, (
            f"suspiciously few requests completed ({total}) — the load "
            "loop itself wedged"
        )
        assert counts["mixed"] == 0, (
            f"{counts['mixed']} answers mixed model iterations"
        )
        assert counts["wrong"] == 0, (
            f"{counts['wrong']} answers diverged from the pre-chaos "
            "reference"
        )
        assert availability >= float(budget["min_availability"]), (
            f"availability {availability:.4f} below budget "
            f"{budget['min_availability']}"
        )
        assert amplification <= float(budget["max_retry_amplification"]), (
            f"retry amplification {amplification:.3f} exceeds budget "
            f"{budget['max_retry_amplification']}"
        )
        assert restarts >= 1, (
            "the SIGKILLed replica was never restarted — supervision "
            "is not working"
        )
        return result
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


# -- phase: alert detection + incident capture -------------------------------


def _read_alert_transitions(run_dir: str) -> list:
    from gene2vec_tpu.obs.alerts import collect_transitions

    return collect_transitions(run_dir)


def _trace_doc_pids(doc: dict) -> set:
    """Every pid a reassembled trace document touches (hop nodes,
    process-local subtrees, flight records)."""
    pids = set(doc.get("processes") or [])

    def walk(node: dict) -> None:
        if node.get("pid"):
            pids.add(node["pid"])
        for sub in node.get("process_spans", []):
            walk(sub)
        for child in node.get("children", []):
            walk(child)

    for root in doc.get("roots", []):
        walk(root)
    for rec in doc.get("flight", []):
        if rec.get("pid"):
            pids.add(rec["pid"])
    return pids


def drill_alerts(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """Measure the detection loop end to end: clean warmup fires NOTHING,
    an injected replica fault fires the availability burn-rate rule
    within the budgeted latency, and the auto-assembled incident bundle
    is CRC-verified and holds a reassembled trace through the faulty
    replica."""
    import glob
    import threading

    from gene2vec_tpu.resilience.faults import FaultSpec
    from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy
    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "alerts_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    replicas = int(budget.get("replicas", 3))
    scrape_s = float(budget.get("scrape_interval_s", 0.25))
    proxy_attempts = int(budget.get("proxy_attempts", 1))
    max_latency = float(budget.get("max_detection_latency_s", 20.0))
    warmup_s = 6.0
    workers = 4
    expected_rule = "availability-burn"

    # The faulty replica is BYZANTINE, not crashed: it answers promptly
    # with 404s for valid requests (a bad deploy / corrupted routing
    # table) plus injected latency, scoped to /v1/similar so the warmup
    # route stays clean.  The fault class is chosen deliberately —
    # retry-safe faults (503s, resets, kills) are ABSORBED by the PR-5
    # resilience layer (per-replica breakers eject a 500-spewing
    # replica within seconds; measured here: 8 of 3285 responses
    # surfaced before the breaker closed the tap), so the front door
    # never shows an SLO burn and nothing SHOULD alert.  A 4xx from a
    # replica is classified replica-healthy (never retried, breaker
    # records success) and forwards straight to the caller: a steady,
    # unabsorbable availability burn — exactly the gray-failure class
    # burn-rate alerting exists to catch.
    faults = FaultSpec(
        seed=seed, route_prefix="/v1/similar",
        latency_p=0.5, latency_ms=180.0,
        error_p=0.5, error_status=404,
    )
    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", str(replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3",
        "--proxy-attempts", str(proxy_attempts),
        "--proxy-timeout-ms", "4000",
        "--scrape-interval", str(scrape_s),
        "--alert-rules", "default",
        "--seed", str(seed),
        # no LRU: a cached answer never touches the batcher/engine, and
        # the bundle's reassembled trace must span the whole pipeline
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--replica-arg", "1:--faults", "--replica-arg",
        f"1:{faults.to_json()}",
    ]
    log(f"spawning fleet: {replicas} replicas, byzantine 404s+latency "
        f"on replica 1 (route-scoped to /v1/similar), default alert "
        f"rules")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        run_dir = info["run_dir"]
        faulty_pid = info["replica_pids"][1]
        log(f"fleet front door at {url}; faulty replica pid {faulty_pid}; "
            f"run dir {run_dir}")

        client = ResilientClient(
            [url],
            RetryPolicy(
                max_attempts=1, default_timeout_s=6.0,
                read_timeout_s=6.0, trace_sample=1.0,
            ),
        )
        query_genes = [f"G{i}" for i in range(8)]
        # prime the /v1/similar compile caches DIRECTLY on every
        # replica, bypassing the proxy: the first top-k batch
        # jit-compiles (~hundreds of ms), and neither the clean-warmup
        # check nor the detection clock may be polluted by it — direct
        # requests never touch the proxy's availability counters.  The
        # faulty replica can 404 a priming request; retry until one
        # compile-carrying 200 lands.
        body = json.dumps(
            {"genes": [query_genes[0]], "k": 4}
        ).encode("utf-8")
        for replica_url in info["replica_urls"]:
            for _ in range(12):
                req = urllib.request.Request(
                    f"{replica_url}/v1/similar", data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    with urllib.request.urlopen(req, timeout=15.0) as r:
                        if r.status == 200:
                            break
                except urllib.error.HTTPError:
                    continue  # injected 404: try again
        # --- clean warmup: load a route the fault spec never matches;
        # ZERO rules may fire.  Lightly paced — the warmup must
        # exercise the pipeline, not flood the burn-rate windows with
        # so much clean traffic that the later fault burn is diluted
        # below its threshold for most of the detection budget.
        log(f"clean warmup ({warmup_s:.0f}s on /v1/embedding)")
        stop_at = time.monotonic() + warmup_s

        def warm_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                client.request("/v1/embedding", {"genes": [g]},
                               timeout_s=6.0)
                time.sleep(0.02)

        threads = [
            threading.Thread(target=warm_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=warmup_s + 30.0)
        time.sleep(max(3 * scrape_s, 1.0))  # let the evaluator tick
        warmup_firings = [
            r for r in _read_alert_transitions(run_dir)
            if r.get("to") == "firing"
        ]
        assert not warmup_firings, (
            f"rule(s) fired during the CLEAN warmup: "
            f"{[r['rule'] for r in warmup_firings]}"
        )
        log("clean warmup: zero rules fired")

        # --- fault exposure: load the faulty route, clock the firing
        t_fault = time.time()
        load_stop = [time.monotonic() + max_latency + 15.0]

        def fault_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 100 + widx)
            while time.monotonic() < load_stop[0]:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                client.request(
                    "/v1/similar", {"genes": [g], "k": 4}, timeout_s=6.0
                )

        threads = [
            threading.Thread(target=fault_worker, args=(w,), daemon=True)
            for w in range(workers)
        ]
        for t in threads:
            t.start()

        def find_firing():
            for r in _read_alert_transitions(run_dir):
                if (
                    r.get("to") == "firing"
                    and r.get("rule") == expected_rule
                    and r.get("wall", 0.0) >= t_fault
                ):
                    return r
            return None

        firing = wait_until(
            find_firing, max_latency + 5.0, interval_s=0.2,
            what=f"rule {expected_rule!r} firing",
        )
        detection_latency = firing["wall"] - t_fault
        log(f"rule {expected_rule!r} fired {detection_latency:.2f}s after "
            f"the first faulty request (budget {max_latency:g}s)")
        # keep load flowing briefly so the bundle's flight rings and
        # trace window are rich, then stop
        time.sleep(2.0)
        load_stop[0] = 0.0
        for t in threads:
            t.join(timeout=30.0)

        # --- the incident bundle: assembled in the proxy process on its
        # own thread; its manifest is written LAST, so waiting for the
        # manifest waits for the whole bundle
        def find_bundle():
            manifests = glob.glob(os.path.join(
                run_dir, "incidents", "*", "incident.MANIFEST.json"
            ))
            # the availability firing's bundle specifically — another
            # rule may legitimately fire later in the fault window
            mine = [
                os.path.dirname(m) for m in manifests
                if os.path.basename(os.path.dirname(m)).split("_", 1)[-1]
                .startswith(expected_rule)
            ]
            return sorted(mine) or None

        bundles = wait_until(find_bundle, 45.0, interval_s=0.5,
                             what="incident bundle manifest")
        bundle = bundles[0]
        # verify through the operator's tool (cli.obs incident: CRC
        # verification + render; exit 0 is the verified contract)
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "incident",
             bundle],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0, (
            f"cli.obs incident failed (rc={cli.returncode}):\n"
            f"{cli.stdout}\n{cli.stderr}"
        )
        assert "VERIFIED" in cli.stdout, cli.stdout
        # ... and the timeline renderer sees the firing
        cli = subprocess.run(
            [sys.executable, "-m", "gene2vec_tpu.cli.obs", "alerts",
             run_dir],
            capture_output=True, text=True, timeout=120,
            env=chaos.child_env(), cwd=REPO,
        )
        assert cli.returncode == 0 and expected_rule in cli.stdout, (
            f"cli.obs alerts missing the firing (rc={cli.returncode}):\n"
            f"{cli.stdout}"
        )

        trace_files = sorted(glob.glob(os.path.join(bundle, "trace-*.json")))
        assert trace_files, "incident bundle reassembled no traces"
        trace_pids = {}
        for path in trace_files:
            with open(path) as f:
                trace_pids[os.path.basename(path)] = _trace_doc_pids(
                    json.load(f)
                )
        through_faulty = [
            name for name, pids in trace_pids.items() if faulty_pid in pids
        ]
        assert through_faulty, (
            f"no bundle trace passes through the faulty replica pid "
            f"{faulty_pid}: {trace_pids}"
        )
        dump_files = sorted(
            os.path.basename(p) for p in
            glob.glob(os.path.join(bundle, "flightdump-*.json"))
        )
        # proxy ring + one dump per live replica — a silently failed
        # /debug/flight fetch (the faulty replica's ring is the
        # interesting one) must fail the drill, not just shrink the
        # bundle
        assert len(dump_files) >= replicas + 1, (
            f"expected flight dumps from the proxy + every live replica "
            f"({replicas + 1}), got {dump_files}"
        )
        assert os.path.exists(
            os.path.join(bundle, "metrics_window.json")
        ), "bundle is missing its raw metrics window"

        all_firings = sorted({
            r["rule"] for r in _read_alert_transitions(run_dir)
            if r.get("to") == "firing"
        })
        result = {
            "replicas": replicas,
            "scrape_interval_s": scrape_s,
            "proxy_attempts": proxy_attempts,
            "warmup_s": warmup_s,
            "workers": workers,
            "expected_rule": expected_rule,
            "fired_rules": all_firings,
            "detection_latency_s": round(detection_latency, 3),
            "warmup_false_positives": len(warmup_firings),
            "bundle": os.path.relpath(bundle, tmp),
            "bundle_verified": True,
            "bundle_traces": len(trace_files),
            "bundle_trace_through_faulty_replica": True,
            "bundle_flight_dumps": len(dump_files),
            "faulty_replica_pid": faulty_pid,
            "faults_spec": faults.to_json(),
            "budget": {k: v for k, v in budget.items()
                       if not k.startswith("_")},
        }
        log(f"alerts: detection {detection_latency:.2f}s, fired "
            f"{all_firings}, bundle {os.path.basename(bundle)} verified "
            f"({len(trace_files)} trace(s), {len(dump_files)} dump(s))")
        assert detection_latency <= max_latency, (
            f"detection latency {detection_latency:.2f}s exceeds budget "
            f"{max_latency:g}s"
        )
        return result
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


# -- phase: elastic autoscaling + tenant isolation ---------------------------


def _parse_labeled_counters(text: str) -> dict:
    """(name, labels) -> value via the aggregator's escape-aware
    parser (labeled tenant series need real label parsing)."""
    from gene2vec_tpu.obs.aggregate import parse_prometheus

    return {(s.name, s.labels): s.value for s in parse_prometheus(text)}


def _fetch_metrics(url: str) -> dict:
    return _parse_prom_counters(
        urllib.request.urlopen(url + "/metrics", timeout=10.0)
        .read().decode("utf-8")
    )


def _replica_states(url: str) -> list:
    return _http_json(url + "/healthz", timeout=10.0)["replicas"]


def drill_autoscale(tmp: str, smoke: bool, budget: dict, seed: int) -> dict:
    """Exercise the elastic fleet end to end: (A) a load ramp must
    produce a scale-up DECISION within the budgeted number of scrape
    ticks; (B) ramp-down must scale back down through the zero-drop
    drain — continuous verified light load sees ZERO dropped, wrong, or
    mixed-iteration answers, and a steady-state window after
    convergence records ZERO further scale actions; (C) an abusive
    tenant flooding far over its token bucket must leave a paced victim
    tenant's availability >= the budget floor, with the abuser's 429s
    landing in the tenant-labeled rejection series."""
    import threading

    from gene2vec_tpu.serve.fleet import read_contract_line

    export_dir = os.path.join(tmp, "autoscale_export")
    _write_iteration(export_dir, 1, vocab_size=48, dim=8)

    min_replicas = int(budget.get("min_replicas", 1))
    max_replicas = int(budget.get("max_replicas", 2))
    scrape_s = float(budget.get("scrape_interval_s", 0.25))
    max_ticks = float(budget.get("max_scale_up_detection_ticks", 40))
    steady_ticks = 16 if smoke else 24
    ramp_workers = 48
    query_genes = [f"G{i}" for i in range(8)]

    argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir,
        "--replicas", str(min_replicas),
        "--min-replicas", str(min_replicas),
        "--max-replicas", str(max_replicas),
        "--port", "0", "--health-interval", "0.25",
        "--backoff-base", "0.3", "--proxy-timeout-ms", "4000",
        "--proxy-workers", "64",
        "--scrape-interval", str(scrape_s),
        "--alert-rules", "none",
        "--seed", str(seed),
        # the scaler's drill knobs: breach fast (2 ticks), clear slow
        # (12 ticks), short cooldown so the smoke finishes, bounded
        # drain
        "--scale-up-queue", "4", "--scale-up-rejection", "0.02",
        "--scale-up-after", "2", "--scale-down-after", "12",
        "--scale-down-queue", "3", "--scale-cooldown", "1.0",
        "--drain-timeout", "15",
        # replica geometry that makes one replica saturable by a CPU
        # drill (the production knee is ~1,200 rps/replica,
        # BENCH_SERVE_r11; here batches of 4 per 100 ms window cap
        # service at ~40 rps, so 48 closed-loop workers keep the
        # 8-deep queue pinned full and shedding): no LRU (cached
        # answers bypass the queue the ramp must fill), long admission
        # window, tiny batch, small bounded queue, enough HTTP workers
        # that admission — not the handler pool — is the choke point
        "--serve-arg=--cache-size", "--serve-arg=0",
        "--serve-arg=--max-delay-ms", "--serve-arg=100",
        "--serve-arg=--max-batch", "--serve-arg=4",
        "--serve-arg=--max-queue", "--serve-arg=8",
        "--serve-arg=--http-workers", "--serve-arg=32",
    ]
    log(f"spawning elastic fleet: {min_replicas} -> {max_replicas} "
        f"replicas, scrape {scrape_s}s")
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, text=True, env=chaos.child_env(),
        cwd=REPO,
    )
    try:
        info = read_contract_line(proc, 180.0)
        url = info["url"]
        assert info.get("autoscale") == {
            "min": min_replicas, "max": max_replicas
        }, f"contract line missing autoscale facts: {info}"
        log(f"elastic fleet front door at {url}")

        def post(gene: str, timeout: float = 10.0,
                 tenant: str = None) -> "tuple":
            """(status, doc-or-None) for one POST /v1/similar."""
            body = json.dumps({"genes": [gene], "k": 4}).encode("utf-8")
            headers = {"Content-Type": "application/json"}
            if tenant:
                headers["X-Tenant"] = tenant
            req = urllib.request.Request(
                url + "/v1/similar", data=body, headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return r.status, json.loads(
                        r.read().decode("utf-8")
                    )
            except urllib.error.HTTPError as e:
                e.read()
                e.close()
                return e.code, None
            except Exception:
                return 0, None

        # pre-ramp reference answers: everything verified during the
        # scale-down window must match these exactly
        reference = {}
        for g in query_genes:
            status, doc = post(g, timeout=15.0)
            assert status == 200, f"reference query failed ({status})"
            reference[g] = (
                doc["model"]["iteration"],
                tuple(n["gene"] for n in doc["results"][0]["neighbors"]),
            )

        base = _fetch_metrics(url)
        assert base.get("fleet_scale_up_total") == 0.0, (
            "scaler acted before the ramp — thresholds are too twitchy"
        )

        # --- (A) the ramp: saturate the single replica's queue --------
        ramp_stop = threading.Event()
        ramp_counts = {"n": 0, "rejected": 0}
        ramp_lock = threading.Lock()

        def ramp_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + widx)
            while not ramp_stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, _ = post(g, timeout=10.0)
                with ramp_lock:
                    ramp_counts["n"] += 1
                    if status == 429:
                        ramp_counts["rejected"] += 1

        t_ramp = time.monotonic()
        ramp_threads = [
            threading.Thread(target=ramp_worker, args=(w,), daemon=True)
            for w in range(ramp_workers)
        ]
        for t in ramp_threads:
            t.start()

        def scale_up_decided():
            m = _fetch_metrics(url)
            return m.get("fleet_scale_up_total", 0.0) >= 1.0 or None

        wait_until(
            scale_up_decided, max_ticks * scrape_s + 10.0,
            interval_s=0.1, what="scale-up decision",
        )
        detection_s = time.monotonic() - t_ramp
        detection_ticks = max(1, int(np.ceil(detection_s / scrape_s)))
        log(f"scale-up decided {detection_s:.2f}s after the ramp "
            f"({detection_ticks} tick(s) at {scrape_s}s; budget "
            f"{max_ticks:g})")

        # completion is bounded separately: a replica spawn is a full
        # jax import on this host
        def scaled_up():
            ups = [
                r for r in _replica_states(url) if r["state"] == "up"
            ]
            return (len(ups) >= max_replicas) or None

        wait_until(scaled_up, 180.0, interval_s=0.5,
                   what="new replica in rotation")
        scale_up_completed_s = time.monotonic() - t_ramp
        log(f"fleet at {max_replicas} replicas "
            f"{scale_up_completed_s:.1f}s after the ramp started")
        ramp_stop.set()
        for t in ramp_threads:
            t.join(timeout=30.0)

        # --- (B) ramp-down under continuous verified light load -------
        light_stop = threading.Event()
        light = {"n": 0, "dropped": 0, "wrong": 0, "mixed": 0}
        light_lock = threading.Lock()

        def light_worker(widx: int) -> None:
            wrng = np.random.RandomState(seed + 500 + widx)
            while not light_stop.is_set():
                g = query_genes[int(wrng.randint(len(query_genes)))]
                status, doc = post(g, timeout=10.0)
                with light_lock:
                    light["n"] += 1
                    if status != 200 or doc is None:
                        # ANY non-200 during scale-down is a drop: the
                        # light load sits far under every threshold, so
                        # the only thing that could fail it is a replica
                        # dying with requests on board
                        light["dropped"] += 1
                        continue
                    ref_it, ref_neighbors = reference[g]
                    it = doc["model"]["iteration"]
                    got = tuple(
                        n["gene"]
                        for n in doc["results"][0]["neighbors"]
                    )
                    if it != ref_it:
                        light["mixed"] += 1
                    elif got != ref_neighbors:
                        light["wrong"] += 1
                time.sleep(0.1)

        light_threads = [
            threading.Thread(target=light_worker, args=(w,), daemon=True)
            for w in range(2)
        ]
        t_down0 = time.monotonic()
        for t in light_threads:
            t.start()

        def scaled_down():
            m = _fetch_metrics(url)
            if m.get("fleet_scale_down_total", 0.0) < 1.0:
                return None
            states = _replica_states(url)
            ups = [r for r in states if r["state"] == "up"]
            return (
                len(states) == min_replicas
                and len(ups) == min_replicas
            ) or None

        # clear window (12 ticks) + drain + cooldown + margin
        wait_until(scaled_down, 12 * scrape_s + 60.0, interval_s=0.5,
                   what="zero-drop scale-down back to min_replicas")
        scale_down_s = time.monotonic() - t_down0
        log(f"scaled back down to {min_replicas} replica(s) in "
            f"{scale_down_s:.1f}s under verified light load")

        # --- steady state: ZERO further actions after convergence -----
        steady_base = _fetch_metrics(url)
        time.sleep(steady_ticks * scrape_s)
        steady_now = _fetch_metrics(url)
        steady_actions = int(
            (steady_now.get("fleet_scale_up_total", 0.0)
             - steady_base.get("fleet_scale_up_total", 0.0))
            + (steady_now.get("fleet_scale_down_total", 0.0)
               - steady_base.get("fleet_scale_down_total", 0.0))
        )
        light_stop.set()
        for t in light_threads:
            t.join(timeout=30.0)
        drain_timeouts = int(
            steady_now.get("fleet_drain_timeouts_total", 0.0)
        )
        log(f"steady state: {steady_actions} scale action(s) over "
            f"{steady_ticks} ticks; light load {light['n']} requests, "
            f"{light['dropped']} dropped, {light['wrong']} wrong, "
            f"{light['mixed']} mixed; drain timeouts {drain_timeouts}")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    # --- (C) tenant isolation: a fresh single-replica fleet with
    # per-tenant token buckets; the abuser floods, the victim paces ----
    victim, abuser = "alice", "mallory"
    tenant_argv = [
        sys.executable, "-m", "gene2vec_tpu.cli.fleet",
        "--export-dir", export_dir, "--replicas", "1",
        "--port", "0", "--health-interval", "0.25",
        "--proxy-timeout-ms", "4000", "--proxy-workers", "64",
        "--scrape-interval", "0.5", "--alert-rules", "none",
        "--seed", str(seed),
        "--serve-arg=--cache-size", "--serve-arg=0",
        # default quota 50 rps (burst 100) for every tenant incl. the
        # abuser; the victim gets an explicit override with a 4x
        # fair-dequeue weight — the drill exercises the override path
        "--serve-arg=--tenant-quota", "--serve-arg=50",
        "--serve-arg=--tenant-override",
        f"--serve-arg={victim}:50:100:4",
    ]
    log("spawning tenant-isolation fleet (1 replica, 50 rps/tenant "
        "token buckets)")
    tduration_s = 6.0 if smoke else 12.0
    proc = subprocess.Popen(
        tenant_argv, stdout=subprocess.PIPE, text=True,
        env=chaos.child_env(), cwd=REPO,
    )
    try:
        from gene2vec_tpu.serve.fleet import read_contract_line

        info = read_contract_line(proc, 180.0)
        turl = info["url"]
        replica_url = info["replica_urls"][0]
        health = _http_json(replica_url + "/healthz", timeout=10.0)
        assert health.get("tenancy", {}).get("default_rate") == 50.0, (
            f"replica healthz shows no tenancy: {health}"
        )

        import threading

        counts = {
            victim: {"n": 0, "ok": 0, "rejected": 0, "lat": []},
            abuser: {"n": 0, "ok": 0, "rejected": 0, "lat": []},
        }
        tlock = threading.Lock()
        stop_at = time.monotonic() + tduration_s

        def tenant_worker(tenant: str, pace_s: float, widx: int) -> None:
            wrng = np.random.RandomState(seed + 900 + widx)
            while time.monotonic() < stop_at:
                g = query_genes[int(wrng.randint(len(query_genes)))]
                body = json.dumps(
                    {"genes": [g], "k": 4}
                ).encode("utf-8")
                req = urllib.request.Request(
                    turl + "/v1/similar", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Tenant": tenant},
                    method="POST",
                )
                t0 = time.monotonic()
                try:
                    with urllib.request.urlopen(req, timeout=10.0) as r:
                        r.read()
                        status = r.status
                except urllib.error.HTTPError as e:
                    e.read()
                    e.close()
                    status = e.code
                except Exception:
                    status = 0
                dur_ms = (time.monotonic() - t0) * 1000.0
                with tlock:
                    c = counts[tenant]
                    c["n"] += 1
                    if status == 200:
                        c["ok"] += 1
                        c["lat"].append(dur_ms)
                    elif status == 429:
                        c["rejected"] += 1
                if pace_s > 0:
                    time.sleep(pace_s)

        # the victim paces at ~20 rps (well inside its 50 rps bucket);
        # the abuser floods unpaced from 8 workers — hundreds of rps
        # against the same 50 rps default bucket
        tenant_threads = [
            threading.Thread(
                target=tenant_worker, args=(victim, 0.05, 0),
                daemon=True,
            )
        ] + [
            threading.Thread(
                target=tenant_worker, args=(abuser, 0.0, 1 + w),
                daemon=True,
            )
            for w in range(8)
        ]
        log(f"tenant isolation: {victim} paced vs {abuser} flooding "
            f"for {tduration_s:g}s")
        for t in tenant_threads:
            t.start()
        for t in tenant_threads:
            t.join(timeout=tduration_s + 60.0)

        v, a = counts[victim], counts[abuser]
        victim_availability = v["ok"] / max(v["n"], 1)
        v["lat"].sort()
        victim_p99_ms = (
            v["lat"][min(len(v["lat"]) - 1, int(0.99 * len(v["lat"])))]
            if v["lat"] else None
        )
        # the labeled rejection series must exist on the replica: WHO
        # was shed is the whole point of the tenant label
        labeled = _parse_labeled_counters(
            urllib.request.urlopen(replica_url + "/metrics", timeout=10.0)
            .read().decode("utf-8")
        )
        abuser_series = labeled.get(
            ("serve_rejected_total", (("tenant", abuser),))
        )
        log(f"tenant isolation: {victim} availability "
            f"{victim_availability:.4f} over {v['n']} requests "
            f"(p99 {victim_p99_ms} ms); {abuser} sent {a['n']}, "
            f"shed {a['rejected']} as 429 "
            f"(labeled series: {abuser_series})")
        assert v["n"] >= tduration_s * 5, (
            f"victim sent suspiciously few requests ({v['n']})"
        )
        assert a["rejected"] > 0, (
            "the abusive tenant was never rejected — quotas are not "
            "enforcing"
        )
        assert abuser_series is not None and abuser_series > 0, (
            f"serve_rejected_total{{tenant={abuser!r}}} missing from "
            "the replica's /metrics"
        )
        min_victim = float(budget.get("min_victim_availability", 0.99))
        assert victim_availability >= min_victim, (
            f"victim tenant availability {victim_availability:.4f} "
            f"below budget {min_victim}"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    result = {
        "min_replicas": min_replicas,
        "max_replicas": max_replicas,
        "scrape_interval_s": scrape_s,
        "scale_up_detection_ticks": detection_ticks,
        "scale_up_detection_s": round(detection_s, 3),
        "scale_up_completed_s": round(scale_up_completed_s, 2),
        "scale_down_s": round(scale_down_s, 2),
        "drain_timeouts": drain_timeouts,
        "ramp_workers": ramp_workers,
        "ramp_requests": ramp_counts["n"],
        "ramp_rejected_429": ramp_counts["rejected"],
        "lightload_requests": light["n"],
        "dropped_answers": light["dropped"],
        "wrong_answers": light["wrong"],
        "mixed_iteration_answers": light["mixed"],
        "steady_state_ticks": steady_ticks,
        "steady_state_scale_actions": steady_actions,
        "victim_tenant": victim,
        "abusive_tenant": abuser,
        "victim_requests": v["n"],
        "victim_ok": v["ok"],
        "victim_tenant_availability": round(victim_availability, 5),
        "victim_p99_ms": (
            round(victim_p99_ms, 2) if victim_p99_ms is not None else None
        ),
        "abuser_requests": a["n"],
        "abuser_rejected_429": a["rejected"],
        "tenant_rejections_labeled": True,
        "budget": {k: val for k, val in budget.items()
                   if not k.startswith("_")},
    }
    assert detection_ticks <= max_ticks, (
        f"scale-up detection took {detection_ticks} tick(s), budget "
        f"{max_ticks:g}"
    )
    assert light["n"] >= 10, (
        f"suspiciously little light load ({light['n']} requests) — "
        "the scale-down window was never really exercised"
    )
    assert light["dropped"] == 0, (
        f"{light['dropped']} request(s) dropped during scale-down — "
        "the drain is not zero-drop"
    )
    assert light["wrong"] == 0, (
        f"{light['wrong']} wrong answer(s) during scale actions"
    )
    assert light["mixed"] == 0, (
        f"{light['mixed']} mixed-iteration answer(s) during scale "
        "actions"
    )
    assert steady_actions == 0, (
        f"{steady_actions} scale action(s) in the steady-state window "
        "— the fleet is flapping"
    )
    return result


# -- phase: async checkpoint overhead ---------------------------------------


def drill_async_overhead(tmp: str, budget: dict) -> dict:
    import dataclasses

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.obs.trace import read_events
    from gene2vec_tpu.sgns.train import SGNSTrainer

    vocab_size = int(budget["vocab"])
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    pairs = rng.choice(
        vocab_size, size=(int(budget["num_pairs"]), 2), p=p
    ).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size)
    corpus = PairCorpus(
        Vocab([f"G{i}" for i in range(vocab_size)], counts.astype(np.int64)),
        pairs,
    )
    base = SGNSConfig(
        dim=int(budget["dim"]), batch_pairs=int(budget["batch_pairs"]),
        num_iters=int(budget["num_iters"]),
        txt_output=bool(budget.get("txt_output", True)),
    )

    def overhead(async_on: bool) -> float:
        cfg = dataclasses.replace(base, async_checkpoint=async_on)
        d = os.path.join(tmp, f"overhead_{'async' if async_on else 'sync'}")
        SGNSTrainer(corpus, cfg).run(d, log=lambda s: None)
        spans = {"iteration": 0.0, "checkpoint": 0.0}
        for e in read_events(os.path.join(d, "events.jsonl")):
            if e.get("type") == "span_end" and e.get("name") in spans:
                spans[e["name"]] += float(e.get("dur", 0.0))
        return spans["checkpoint"] / max(spans["iteration"], 1e-9)

    sync_frac = overhead(False)
    async_frac = overhead(True)
    log(f"checkpoint span / epoch wall: sync {sync_frac:.4f}, "
        f"async {async_frac:.4f} (budget {budget['max_overhead_fraction']})")
    assert async_frac < float(budget["max_overhead_fraction"]), (
        f"async checkpoint overhead {async_frac:.4f} exceeds "
        f"{budget['max_overhead_fraction']}"
    )
    return {
        "geometry": {k: budget[k] for k in
                     ("dim", "vocab", "batch_pairs", "num_pairs", "num_iters")},
        "sync_overhead_fraction": round(sync_frac, 5),
        "async_overhead_fraction": round(async_frac, 5),
        "max_overhead_fraction": budget["max_overhead_fraction"],
    }


# -- driver ------------------------------------------------------------------


PHASES = ("training_resume", "corruption", "serve", "async_overhead",
          "fleet", "alerts", "autoscale")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_drill",
        description="fault-injection drill for the resilience subsystem",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized drill: fewer iterations per phase")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--fleet-out", default=None, metavar="PATH",
                    help="also write the fleet phase's results (plus "
                         "budget) as a standalone bench document, e.g. "
                         "BENCH_FLEET_r08.json — the record "
                         "analysis/passes_fleet.py gates on")
    ap.add_argument("--alerts-out", default=None, metavar="PATH",
                    help="also write the alerts phase's results (plus "
                         "budget) as a standalone bench document, e.g. "
                         "BENCH_ALERTS_r13.json — the record "
                         "analysis/passes_alerts.py gates on")
    ap.add_argument("--autoscale-out", default=None, metavar="PATH",
                    help="also write the autoscale phase's results "
                         "(plus budget) as a standalone bench document, "
                         "e.g. BENCH_AUTOSCALE_r14.json — the record "
                         "analysis/passes_autoscale.py gates on")
    ap.add_argument("--only", default=None,
                    help=f"comma-separated phases from {PHASES}")
    ap.add_argument("--seed", type=int, default=None,
                    help="kill-point seed (default: derived from time)")
    ap.add_argument("--tmp", default=None, help="work dir (default: mkdtemp)")
    args = ap.parse_args(argv)

    only = args.only.split(",") if args.only else list(PHASES)
    unknown = [p for p in only if p not in PHASES]
    if unknown:
        ap.error(f"unknown phase(s) {unknown}; choose from {PHASES}")

    # the async_overhead phase trains IN-PROCESS: pin the CPU backend
    # before jax initializes, exactly like chaos.child_env does for the
    # child phases — the session env may point at a real accelerator,
    # and the overhead budget's reference numbers are CPU-derived
    os.environ["JAX_PLATFORMS"] = "cpu"

    import tempfile

    from gene2vec_tpu.analysis.passes_hlo import load_budgets

    tmp = args.tmp or tempfile.mkdtemp(prefix="chaos_drill_")
    seed = args.seed if args.seed is not None else int(time.time()) % 100000
    budgets = load_budgets()
    budget = budgets["resilience"]["async_ckpt"]
    fleet_budget = budgets["fleet"]["chaos"]
    alerts_budget = budgets["alerts"]["detection"]
    autoscale_budget = budgets["autoscale"]["elasticity"]
    iters = 3 if args.smoke else 5

    doc = {
        "schema": "gene2vec-tpu/chaos-drill/v1",
        # provenance stamp (ledger contract, docs/BENCHMARKS.md)
        "schema_version": 1,
        "command": " ".join([sys.executable, *sys.argv]),
        "created_unix": time.time(),
        "host": socket.gethostname(),
        "smoke": bool(args.smoke),
        "seed": seed,
        "phases": {},
        "passed": False,
    }
    t0 = time.monotonic()
    failed = None
    for phase in only:
        log(f"=== phase: {phase} ===")
        try:
            if phase == "training_resume":
                doc["phases"][phase] = drill_training_resume(tmp, iters, seed)
            elif phase == "corruption":
                doc["phases"][phase] = drill_corruption(tmp)
            elif phase == "serve":
                doc["phases"][phase] = drill_serve(tmp)
            elif phase == "async_overhead":
                doc["phases"][phase] = drill_async_overhead(tmp, budget)
            elif phase == "fleet":
                doc["phases"][phase] = drill_fleet(
                    tmp, args.smoke, fleet_budget, seed
                )
            elif phase == "alerts":
                doc["phases"][phase] = drill_alerts(
                    tmp, args.smoke, alerts_budget, seed
                )
            elif phase == "autoscale":
                doc["phases"][phase] = drill_autoscale(
                    tmp, args.smoke, autoscale_budget, seed
                )
        except Exception as e:
            failed = f"{phase}: {e}"
            doc["phases"][phase] = {"error": str(e)}
            log(f"PHASE FAILED — {e}")
            break
    doc["wall_seconds"] = round(time.monotonic() - t0, 2)
    doc["passed"] = failed is None
    if failed:
        doc["failed"] = failed

    blob = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
        log(f"wrote {args.out}")
    if args.fleet_out and "fleet" in doc["phases"]:
        fleet_doc = {
            "schema": "gene2vec-tpu/bench-fleet/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "fleet_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["fleet"],
            "fleet": doc["phases"]["fleet"],
        }
        with open(args.fleet_out, "w") as f:
            f.write(json.dumps(fleet_doc, indent=1) + "\n")
        log(f"wrote {args.fleet_out}")
    if args.alerts_out and "alerts" in doc["phases"]:
        alerts_doc = {
            "schema": "gene2vec-tpu/bench-alerts/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "alerts_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["alerts"],
            "alerts": doc["phases"]["alerts"],
        }
        with open(args.alerts_out, "w") as f:
            f.write(json.dumps(alerts_doc, indent=1) + "\n")
        log(f"wrote {args.alerts_out}")
    if args.autoscale_out and "autoscale" in doc["phases"]:
        autoscale_doc = {
            "schema": "gene2vec-tpu/bench-autoscale/v1",
            "schema_version": 1,
            "command": doc["command"],
            "bench": "autoscale_chaos_drill",
            "created_unix": doc["created_unix"],
            "host": doc["host"],
            "smoke": doc["smoke"],
            "seed": seed,
            "passed": "error" not in doc["phases"]["autoscale"],
            "autoscale": doc["phases"]["autoscale"],
        }
        with open(args.autoscale_out, "w") as f:
            f.write(json.dumps(autoscale_doc, indent=1) + "\n")
        log(f"wrote {args.autoscale_out}")
    print(blob)
    log("DRILL PASSED" if doc["passed"] else "DRILL FAILED")
    return 0 if doc["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
