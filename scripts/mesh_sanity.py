"""8-way CPU-mesh sanity table for the data-parallel bench path
(VERDICT r3 item 2): runs the bench headline configuration (scaled
down) on 1/2/4/8-device meshes of the FORCED-CPU backend, pinning

* loss parity — the sharded epoch must reproduce the single-device
  epoch's loss to float tolerance (the collectives XLA inserts for the
  scatter-into-replicated-table updates are exact), and
* bounded per-device overhead — the mesh path's single-device-equivalent
  rate must stay within a sane factor of the unsharded rate (on CPU the
  collectives are memcpys; this is a plumbing check, not a perf claim —
  the perf number comes from ``bench.py --mesh-data N`` on real chips).

Corpus and timing discipline are imported from bench.py itself
(``synth_corpus``, ``_steady_rate``) so the table cannot desynchronize
from the headline recipe.  Writes MESH_SANITY_r05.json at the repo
root.  Forced-CPU because the bench host has one TPU chip; the same
``bench.py --mesh-data 8`` command produces the real multi-chip number
when hardware is attached.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

# sitecustomize imports jax before us, so env vars are latched — re-pin
# through the config API (docs/DISTRIBUTED.md; round-3 lesson)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

from bench import _steady_rate, synth_corpus  # noqa: E402
from gene2vec_tpu.config import MeshConfig, SGNSConfig  # noqa: E402
from gene2vec_tpu.parallel.mesh import make_mesh  # noqa: E402
from gene2vec_tpu.parallel.sharding import SGNSSharding  # noqa: E402
from gene2vec_tpu.sgns.train import SGNSTrainer  # noqa: E402

V, D, N, B = 4096, 64, 262_144, 4096


def run(n_devices: int) -> dict:
    corpus = synth_corpus(V, N)
    # pin the dense-head batch layout to 8 blocks at EVERY device count:
    # the per-device [HH|HT|TT] block layout changes example order (not
    # the example set), so loss parity across mesh sizes needs all rows
    # on the same layout (config.pos_layout_shards docs)
    cfg = SGNSConfig(dim=D, batch_pairs=B, pos_layout_shards=8)
    sharding = None
    if n_devices > 1:
        mesh = make_mesh(
            MeshConfig(data=n_devices, model=1),
            devices=jax.devices()[:n_devices],
        )
        sharding = SGNSSharding(mesh, vocab_sharded=False)
    trainer = SGNSTrainer(corpus, cfg, sharding=sharding)

    # loss parity probe: one epoch from the same fresh init/key as every
    # other mesh size (before _steady_rate's own init/warmup)
    params = trainer.init()
    key = jax.random.PRNGKey(42)
    params, loss = trainer.train_epoch(params, key)
    loss = float(loss)
    params, loss2 = trainer.train_epoch(params, jax.random.fold_in(key, 1))
    loss2 = float(loss2)

    # steady-state rate with the bench's own discipline (2 warmup epochs —
    # compile + donated-buffer relayout — then the median of 3 timed)
    rate = _steady_rate(trainer)
    return {
        "devices": n_devices,
        "loss_epoch1": round(loss, 6),
        "loss_epoch2": round(loss2, 6),
        "pairs_per_sec": round(rate, 1),
    }


def main():
    assert jax.device_count() == 8, jax.devices()
    rows = [run(n) for n in (1, 2, 4, 8)]
    ref = rows[0]
    for r in rows[1:]:
        # loss parity: identical seed/config => the mesh changes only the
        # physical layout; any drift means a collective is wrong
        for k in ("loss_epoch1", "loss_epoch2"):
            assert abs(r[k] - ref[k]) < 1e-3, (k, r, ref)
        r["loss_parity"] = True
        # per-device overhead bound: N CPU "devices" share the same host
        # cores, so aggregate throughput CANNOT scale — we bound the
        # mesh-plumbing SLOWDOWN instead (collectives + sharded shuffle)
        r["overhead_factor"] = round(ref["pairs_per_sec"] / r["pairs_per_sec"], 2)
        assert r["overhead_factor"] < 4.0, r
    out = {
        "note": (
            "forced-CPU 8-device mesh (one real chip on the bench host); "
            "loss parity proves the data-parallel collectives exact; "
            "overhead_factor is single-device rate / mesh rate on SHARED "
            "host cores (mesh plumbing cost, not a scaling measurement). "
            "Real multi-chip: bench.py --mesh-data N."
        ),
        "config": {"V": V, "dim": D, "pairs": N, "batch": B},
        "rows": rows,
    }
    with open(os.path.join(REPO, "MESH_SANITY_r05.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
