#!/usr/bin/env python
"""Load generator for the serve subsystem: latency/throughput/rejection
curves vs offered load.

::

    # against a running server
    python scripts/serve_loadgen.py --url http://127.0.0.1:8000 \
        --mode open --levels 50,200,800 --duration 5 \
        --output BENCH_SERVE_r06.json

    # spawn `python -m gene2vec_tpu.cli.serve` on an export dir first
    python scripts/serve_loadgen.py --spawn exports/ --levels 50,200,800

Two loops:

* **open** — ``--levels`` are offered request rates (rps); arrivals are
  paced on a fixed schedule regardless of completions, so queue growth /
  backpressure at overload is visible (429s count into
  ``rejection_rate``, they never stall the clock);
* **closed** — ``--levels`` are concurrency (N workers firing
  back-to-back), the classic saturation-throughput measurement.

Per level: p50/p99/mean latency over successful requests, achieved
throughput, and a full **error-class breakdown** — 429 (backpressure)
vs 503 (not ready) vs 504 (deadline) vs transport (connect/read
failure) vs other HTTP — so an availability claim is auditable down to
*why* requests failed.  With ``--resilient`` every request goes through
:class:`gene2vec_tpu.serve.client.ResilientClient` (retries, breakers,
optional ``--hedge``) and each level additionally reports retry/hedge
counts and the attempt amplification factor.  The JSON document goes to
``--output`` and stdout (the product — progress chatter is stderr-only,
matching the repo's stdout discipline).

Tracing hooks (docs/OBSERVABILITY.md#distributed-tracing):

* ``--trace-sample N`` — every request carries a SAMPLED traceparent
  root, and each level's row reports the trace ids of its N slowest
  requests (``slowest_traces``), so a bench regression comes with
  directly inspectable traces: ``python -m gene2vec_tpu.cli.obs trace
  <export_dir> <trace_id>``;
* ``--trace-overhead`` — the budgets.json ``obs`` gate's measurement:
  one level run twice per round (no header vs sampled header) with the
  arm order alternating per round; each arm's estimate is the MEDIAN
  of its per-window p50s, compared into a ``trace_overhead`` section
  (``BENCH_OBS_r09.json``; ``analysis/passes_obs.py`` re-gates the
  committed record).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

# --resilient imports gene2vec_tpu.serve.client; make `python
# scripts/serve_loadgen.py` work from anywhere, like chaos_drill.py
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from gene2vec_tpu.obs import tracecontext  # noqa: E402
from gene2vec_tpu.obs.tracecontext import TRACEPARENT_HEADER  # noqa: E402


def _http_json(
    url: str, body: Optional[dict] = None, timeout: float = 10.0
) -> Dict:
    data = json.dumps(body).encode("utf-8") if body is not None else None
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


class _Stats:
    """Thread-safe request accounting for one load level, bucketed by
    error class (429 vs 503 vs 504 vs transport vs other) plus the
    resilient-client retry/hedge tallies when that path is active."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.ok = 0
        self.rejected = 0          # 429: explicit backpressure
        self.not_ready = 0         # 503: no model / replica down
        self.expired = 0           # 504: deadline (queue or compute)
        self.transport = 0         # connect refused/reset, read timeout
        self.other_http = 0        # 400s, 500s, anything else
        self.retries = 0
        self.hedges = 0
        self.attempts = 0
        self.traces: List[tuple] = []  # (latency_ms, status, trace_id)

    def record(self, status: int, latency_ms: float,
               retries: int = 0, hedged: bool = False,
               attempts: int = 1, trace_id: Optional[str] = None) -> None:
        with self.lock:
            self.retries += retries
            self.hedges += int(hedged)
            self.attempts += attempts
            if trace_id is not None:
                self.traces.append((latency_ms, status, trace_id))
            if status == 200:
                self.ok += 1
                self.latencies_ms.append(latency_ms)
            elif status == 429:
                self.rejected += 1
            elif status == 503:
                self.not_ready += 1
            elif status == 504:
                self.expired += 1
            elif status <= 0:
                self.transport += 1
            else:
                self.other_http += 1

    @property
    def total(self) -> int:
        return (self.ok + self.rejected + self.not_ready + self.expired
                + self.transport + self.other_http)


def _percentile(sorted_values: List[float], q: float) -> Optional[float]:
    if not sorted_values:
        return None
    i = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[i]


def _one_request(url: str, genes: List[str], k: int, rng: random.Random,
                 stats: _Stats, timeout_s: float,
                 client=None, trace: bool = False) -> None:
    body = {"genes": [rng.choice(genes)], "k": k}
    # when tracing, THIS request is a sampled trace root: the resilient
    # client adopts it as the ambient base (child span per attempt), the
    # plain path sends it as the traceparent header directly
    ctx = tracecontext.new_trace(sampled=True) if trace else None
    if client is not None:
        # the resilient path: retries/hedging under one deadline, with
        # per-request attempt accounting for the amplification report
        with tracecontext.use(ctx):
            r = client.request("/v1/similar", body, timeout_s=timeout_s)
        status = r.status
        if status == 0:
            # no HTTP status reached the caller: bucket the client's own
            # deadline exhaustion with the 504s, transport trouble apart
            status = 504 if r.error_class == "deadline" else -1
        stats.record(
            status,
            r.latency_s * 1000.0,
            retries=r.retries, hedged=r.hedged, attempts=r.attempts,
            trace_id=r.trace_id if trace else None,
        )
        return
    t0 = time.monotonic()
    try:
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            headers[TRACEPARENT_HEADER] = ctx.to_header()
        req = urllib.request.Request(
            f"{url}/v1/similar",
            data=json.dumps(body).encode("utf-8"),
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=timeout_s):
            pass
        status = 200
    except urllib.error.HTTPError as e:
        status = e.code
        e.close()
    except Exception:
        status = -1
    stats.record(
        status, (time.monotonic() - t0) * 1000.0,
        trace_id=ctx.trace_id if ctx is not None else None,
    )


def run_open_level(url: str, genes: List[str], k: int, rps: float,
                   duration_s: float, seed: int, timeout_s: float,
                   client=None, trace: bool = False) -> _Stats:
    """Fixed-schedule arrivals at ``rps`` for ``duration_s``; each
    arrival gets its own thread so a slow/queued response never delays
    the next arrival (that is what makes the loop open)."""
    stats = _Stats()
    rng = random.Random(seed)
    threads: List[threading.Thread] = []
    interval = 1.0 / rps
    t_start = time.monotonic()
    n = int(rps * duration_s)
    for i in range(n):
        target = t_start + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(
            target=_one_request,
            args=(url, genes, k, rng, stats, timeout_s, client, trace),
            daemon=True,
        )
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=timeout_s + 5.0)
    stats.wall_s = time.monotonic() - t_start  # type: ignore[attr-defined]
    return stats


def run_closed_level(url: str, genes: List[str], k: int, workers: int,
                     duration_s: float, seed: int,
                     timeout_s: float, client=None,
                     trace: bool = False) -> _Stats:
    """N workers firing back-to-back until the clock runs out."""
    stats = _Stats()
    stop = time.monotonic() + duration_s

    def loop(worker_seed: int) -> None:
        rng = random.Random(worker_seed)
        while time.monotonic() < stop:
            _one_request(url, genes, k, rng, stats, timeout_s, client,
                         trace)

    t_start = time.monotonic()
    threads = [
        threading.Thread(target=loop, args=(seed + w,), daemon=True)
        for w in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + timeout_s + 5.0)
    stats.wall_s = time.monotonic() - t_start  # type: ignore[attr-defined]
    return stats


def summarize(level: float, stats: _Stats, mode: str,
              resilient: bool = False, trace_sample: int = 0) -> Dict:
    lat = sorted(stats.latencies_ms)
    wall = getattr(stats, "wall_s", 1.0) or 1.0
    row = {
        ("offered_rps" if mode == "open" else "concurrency"): level,
        "requests": stats.total,
        "ok": stats.ok,
        "rejected_429": stats.rejected,
        "not_ready_503": stats.not_ready,
        "expired_504": stats.expired,
        "transport_errors": stats.transport,
        "other_http_errors": stats.other_http,
        "availability": round(
            stats.ok / stats.total, 4
        ) if stats.total else None,
        "achieved_rps": round(stats.ok / wall, 2),
        "rejection_rate": round(
            stats.rejected / stats.total, 4
        ) if stats.total else None,
        "p50_ms": round(_percentile(lat, 0.50), 3) if lat else None,
        "p99_ms": round(_percentile(lat, 0.99), 3) if lat else None,
        "mean_ms": round(sum(lat) / len(lat), 3) if lat else None,
        "wall_s": round(wall, 3),
    }
    if resilient:
        row["retries"] = stats.retries
        row["hedges"] = stats.hedges
        row["attempts"] = stats.attempts
        row["attempt_amplification"] = round(
            stats.attempts / stats.total, 4
        ) if stats.total else None
    if trace_sample > 0 and stats.traces:
        # the N slowest requests, with the trace ids to go look at:
        # `python -m gene2vec_tpu.cli.obs trace <run_dir> <trace_id>`
        slowest = sorted(stats.traces, reverse=True)[:trace_sample]
        row["slowest_traces"] = [
            {"latency_ms": round(lat, 3), "status": status,
             "trace_id": tid}
            for lat, status, tid in slowest
        ]
    return row


def spawn_server(export_dir: str, extra: List[str]) -> "tuple":
    """Launch ``python -m gene2vec_tpu.cli.serve`` and parse its one
    stdout JSON status line for the bound URL."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "gene2vec_tpu.cli.serve",
         "--export-dir", export_dir, "--port", "0", *extra],
        stdout=subprocess.PIPE,
        stderr=sys.stderr,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise RuntimeError(
            f"serve CLI exited rc={proc.returncode} before reporting a URL"
        )
    info = json.loads(line)
    return proc, info


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_loadgen",
        description="Closed/open-loop load generator for the serve API.",
    )
    ap.add_argument("--url", default=None, help="target server base URL")
    ap.add_argument("--spawn", default=None, metavar="EXPORT_DIR",
                    help="spawn cli.serve on this export dir instead of "
                         "--url")
    ap.add_argument("--spawn-arg", action="append", default=[],
                    help="extra flag passed through to the spawned "
                         "cli.serve (repeatable)")
    ap.add_argument("--mode", choices=("open", "closed"), default="open")
    ap.add_argument("--levels", default="50,200,800",
                    help="comma-separated offered rps (open) or worker "
                         "counts (closed)")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per level")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--num-genes", type=int, default=256,
                    help="distinct query genes sampled from /v1/genes")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="client-side socket timeout (s)")
    ap.add_argument("--resilient", action="store_true",
                    help="route through gene2vec_tpu.serve.client."
                         "ResilientClient (retries + breakers; reports "
                         "retry/hedge counts per level)")
    ap.add_argument("--retries", type=int, default=3,
                    help="resilient client max attempts per request")
    ap.add_argument("--hedge", action="store_true",
                    help="enable p95 hedging on the resilient client")
    ap.add_argument("--trace-sample", type=int, default=0, metavar="N",
                    help="send a sampled traceparent root on EVERY "
                         "request and report the N slowest requests' "
                         "trace ids per level")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="measure traced-vs-untraced p50 at ONE level "
                         "(interleaved arms; emits the trace_overhead "
                         "section analysis/passes_obs.py gates)")
    ap.add_argument("--overhead-rounds", type=int, default=3,
                    help="untraced/traced round pairs for "
                         "--trace-overhead")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--warmup", type=int, default=64,
                    help="largest warm-up burst; concurrent bursts of "
                         "1,2,4,...,N give the batcher a chance to form "
                         "each batch bucket so jit compiles land before "
                         "the first measured level")
    ap.add_argument("--output", default="BENCH_SERVE_r06.json")
    args = ap.parse_args(argv)
    if (args.url is None) == (args.spawn is None):
        print("error: provide exactly one of --url / --spawn",
              file=sys.stderr)
        return 2

    proc = None
    try:
        if args.spawn is not None:
            proc, info = spawn_server(args.spawn, args.spawn_arg)
            url = info["url"]
            print(f"spawned serve at {url} (iteration "
                  f"{info['iteration']})", file=sys.stderr)
        else:
            url = args.url.rstrip("/")

        try:
            health = _http_json(f"{url}/healthz", timeout=args.timeout)
        except urllib.error.HTTPError as e:
            # readiness probes 503 until a model is served (or a fleet
            # has a replica in rotation) — report it, don't traceback
            print(
                f"error: {url}/healthz returned {e.code} — the server "
                "is not ready (no model loaded / no replica in rotation)",
                file=sys.stderr,
            )
            e.close()
            return 2
        genes_doc = _http_json(
            f"{url}/v1/genes?limit={args.num_genes}", timeout=args.timeout
        )
        genes = genes_doc["genes"]
        if not genes:
            print("error: server reports an empty vocab", file=sys.stderr)
            return 2

        client = None
        if args.resilient:
            from gene2vec_tpu.serve.client import (
                ResilientClient,
                RetryPolicy,
            )

            client = ResilientClient(
                [url],
                RetryPolicy(
                    max_attempts=args.retries,
                    read_timeout_s=args.timeout,
                    default_timeout_s=args.timeout,
                    hedge=args.hedge,
                ),
                rng=random.Random(args.seed),
            )

        rng = random.Random(args.seed)
        burst = 1
        while burst <= max(1, args.warmup):
            stats = _Stats()
            threads = [
                threading.Thread(
                    target=_one_request,
                    args=(url, genes, args.k, rng, stats, args.timeout,
                          client),
                    daemon=True,
                )
                for _ in range(burst)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=args.timeout + 5.0)
            burst *= 2

        levels = [float(x) for x in args.levels.split(",") if x]
        trace_all = args.trace_sample > 0

        def run_level(level: float, trace: bool) -> _Stats:
            if args.mode == "open":
                return run_open_level(
                    url, genes, args.k, level, args.duration, args.seed,
                    args.timeout, client, trace=trace,
                )
            return run_closed_level(
                url, genes, args.k, int(level), args.duration,
                args.seed, args.timeout, client, trace=trace,
            )

        results = []
        overhead = None
        if args.trace_overhead:
            if len(levels) != 1:
                print("error: --trace-overhead needs exactly one level "
                      "(the budgeted rps)", file=sys.stderr)
                return 2
            level = levels[0]
            # one discarded window at the measured level first: the
            # burst warmup above compiles the small batch buckets, but
            # the first sustained window still pays cold caches, and
            # whichever arm ran first would eat that bias
            print(f"overhead warm window level {level:g} ...",
                  file=sys.stderr)
            run_level(level, False)
            # per-arm estimate = MEDIAN of per-window p50s, arms
            # alternating order each round: this host's window p50s
            # swing several-x between identical windows (a single
            # melted window poisons pooled latencies), and the median
            # over windows shrugs off the outliers both arms suffer
            window_p50s = {False: [], True: []}
            n_per_arm = {False: 0, True: 0}
            for rnd in range(max(1, args.overhead_rounds)):
                order = (False, True) if rnd % 2 == 0 else (True, False)
                for traced in order:
                    arm = "traced" if traced else "untraced"
                    print(f"overhead round {rnd + 1}/"
                          f"{args.overhead_rounds} {arm} level "
                          f"{level:g} ...", file=sys.stderr)
                    stats = run_level(level, traced)
                    w50 = _percentile(sorted(stats.latencies_ms), 0.50)
                    if w50 is not None:
                        window_p50s[traced].append(w50)
                        n_per_arm[traced] += len(stats.latencies_ms)
                    row = summarize(level, stats, args.mode,
                                    args.resilient)
                    row["arm"] = arm
                    row["round"] = rnd + 1
                    results.append(row)

            p50_u = _percentile(sorted(window_p50s[False]), 0.50)
            p50_t = _percentile(sorted(window_p50s[True]), 0.50)
            if not p50_u or p50_t is None:
                print("error: no successful requests in an arm — "
                      "overhead is unmeasurable", file=sys.stderr)
                return 2
            overhead = {
                "rps": level,
                "mode": args.mode,
                "duration_s": args.duration,
                "rounds": args.overhead_rounds,
                "n_untraced": n_per_arm[False],
                "n_traced": n_per_arm[True],
                "window_p50s_untraced_ms": [
                    round(v, 3) for v in window_p50s[False]
                ],
                "window_p50s_traced_ms": [
                    round(v, 3) for v in window_p50s[True]
                ],
                "p50_untraced_ms": round(p50_u, 3),
                "p50_traced_ms": round(p50_t, 3),
                "regression_frac": round((p50_t - p50_u) / p50_u, 4),
            }
            print(f"trace overhead: {json.dumps(overhead)}",
                  file=sys.stderr)
        else:
            for level in levels:
                print(f"level {level:g} ({args.mode}) for "
                      f"{args.duration:g}s ...", file=sys.stderr)
                stats = run_level(level, trace_all)
                row = summarize(level, stats, args.mode, args.resilient,
                                trace_sample=args.trace_sample)
                print(f"  -> {json.dumps(row)}", file=sys.stderr)
                results.append(row)

        doc = {
            # provenance stamp (ledger contract, docs/BENCHMARKS.md):
            # adapters treat records without schema_version as legacy
            "schema_version": 1,
            "command": " ".join([sys.executable, *sys.argv]),
            "created_unix": time.time(),
            "bench": ("trace_overhead" if args.trace_overhead
                      else "serve_loadgen"),
            "mode": args.mode,
            "k": args.k,
            "duration_s": args.duration,
            "num_query_genes": len(genes),
            "server": health.get("model", {}),
            "resilient": bool(args.resilient),
            "trace_sample": args.trace_sample,
            "levels": results,
        }
        if overhead is not None:
            doc["trace_overhead"] = overhead
        if client is not None:
            doc["client_stats"] = dict(client.stats)
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        # the one stdout line is the product; chatter above is stderr
        print(json.dumps(doc), file=sys.stdout)
        return 0
    finally:
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
