#!/usr/bin/env python
"""Load generator for the serve subsystem: latency/throughput/rejection
curves vs offered load, with keep-alive connection reuse.

::

    # against a running server
    python scripts/serve_loadgen.py --url http://127.0.0.1:8000 \
        --mode open --levels 50,200,800 --duration 5 \
        --output BENCH_SERVE_r11.json

    # spawn `python -m gene2vec_tpu.cli.serve` on an export dir first
    python scripts/serve_loadgen.py --spawn exports/ --levels 50,200,800

    # + a 3-replica fleet phase through the front-door proxy
    python scripts/serve_loadgen.py --spawn exports/ --fleet 3 \
        --fleet-levels 500,1000 --verify

Two loops:

* **open** — ``--levels`` are offered request rates (rps); arrivals are
  paced on a fixed schedule regardless of completions and handed to a
  pool of sender workers (``--open-workers``), each holding ONE
  persistent keep-alive connection.  Latency is measured from the
  *scheduled* arrival time, so local queueing under overload counts
  against the server exactly like remote queueing does;
* **closed** — ``--levels`` are concurrency (N workers firing
  back-to-back on persistent connections), the classic saturation-
  throughput measurement.

Connection reuse is the point: the pre-keep-alive loadgen paid a TCP
handshake per request, so the bench measured connection setup, not the
server (BENCH_SERVE_r06's 150-rps knee was substantially the
front-end's thread-per-connection cost — see docs/SERVING.md).  Every
level now reports ``connections_opened`` next to its attempt counts so
a reuse regression is visible in the record.

Per level: p50/p99/mean latency over successful requests, achieved
throughput, availability, and a full **error-class breakdown** — 429
(backpressure) vs 503 (not ready) vs 504 (deadline) vs transport vs
other HTTP.  ``--method get`` exercises the event-loop front end's hot
read path (``GET /v1/similar?gene=...`` — response-bytes cache +
request coalescing); the default ``post`` exercises the full dispatch
pipeline.  ``--verify`` fetches a reference answer per query gene
before each phase and checks every 200 response against it, counting
``wrong_answers`` and ``mixed_iteration_answers`` (the fleet-phase
integrity gate).  ``--tenant id[:weight]`` (repeatable) emits
mixed-tenant traffic — each request draws a tenant by weight and
carries it as ``X-Tenant`` — and every level row gains a per-tenant
requests/ok/429/availability/p50/p99 breakdown, the measurement the
multi-tenant isolation drill and capacity planning both read
(docs/SERVING.md#multi-tenant-admission).  With ``--resilient`` every
request goes through
:class:`gene2vec_tpu.serve.client.ResilientClient` (retries, breakers,
optional ``--hedge``, pooled keep-alive transport) and each level
additionally reports retry/hedge counts and attempt amplification.

The document ends with a ``capacity`` section — the highest level that
sustained offered load under the latency/availability criteria
(``--capacity-p99-ms``, ``--capacity-availability``) — which
``analysis/passes_serve.py`` gates against budgets.json ``serve.
capacity_rps``.  ``--assert-capacity RPS`` (and
``--assert-fleet-capacity RPS``) turn a shortfall into exit 1 for
CI smokes.  The JSON goes to ``--output`` and stdout (the product —
progress chatter is stderr-only, matching the repo's stdout
discipline).

Tracing hooks (docs/OBSERVABILITY.md#distributed-tracing):

* ``--trace-sample N`` — every request carries a SAMPLED traceparent
  root, and each level's row reports the trace ids of its N slowest
  requests (``slowest_traces``);
* ``--trace-overhead`` — the budgets.json ``obs`` gate's measurement:
  one level run twice per round (no header vs sampled header) with the
  arm order alternating per round; each arm's estimate is the MEDIAN
  of its per-window p50s (``BENCH_OBS_r09.json``;
  ``analysis/passes_obs.py`` re-gates the committed record).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import queue as queue_mod
import random
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, urlparse

# --resilient imports gene2vec_tpu.serve.client; make `python
# scripts/serve_loadgen.py` work from anywhere, like chaos_drill.py
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from gene2vec_tpu.obs import tracecontext  # noqa: E402
from gene2vec_tpu.obs.tracecontext import TRACEPARENT_HEADER  # noqa: E402


def _http_json(
    url: str, body: Optional[dict] = None, timeout: float = 10.0
) -> Dict:
    data = json.dumps(body).encode("utf-8") if body is not None else None
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


class _Stats:
    """Thread-safe request accounting for one load level, bucketed by
    error class (429 vs 503 vs 504 vs transport vs other) plus the
    resilient-client retry/hedge tallies, connection-reuse accounting,
    and (``--verify``) answer-integrity counts."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.ok = 0
        self.rejected = 0          # 429: explicit backpressure
        self.not_ready = 0         # 503: no model / replica down
        self.expired = 0           # 504: deadline (queue or compute)
        self.transport = 0         # connect refused/reset, read timeout
        self.other_http = 0        # 400s, 500s, anything else
        self.retries = 0
        self.hedges = 0
        self.attempts = 0
        self.connections_opened = 0
        self.wrong_answers = 0
        self.mixed_iteration_answers = 0
        # sharded-fleet verification (serve/shardgroup.py): responses
        # flagged `degraded` are partial by contract, scored against
        # the reference RESTRICTED to the shards that answered — never
        # counted wrong for missing the dead shard's rows
        self.degraded = 0
        self.degraded_wrong = 0
        self.traces: List[tuple] = []  # (latency_ms, status, trace_id)
        # --tenant mode: per-tenant sub-accounting so the isolation
        # story (availability/429s/p99 per tenant) survives the merge
        self.tenants: Dict[str, Dict] = {}

    def record(self, status: int, latency_ms: float,
               retries: int = 0, hedged: bool = False,
               attempts: int = 1, trace_id: Optional[str] = None,
               tenant: Optional[str] = None) -> None:
        with self.lock:
            self.retries += retries
            self.hedges += int(hedged)
            self.attempts += attempts
            if trace_id is not None:
                self.traces.append((latency_ms, status, trace_id))
            if status == 200:
                self.ok += 1
                self.latencies_ms.append(latency_ms)
            elif status == 429:
                self.rejected += 1
            elif status == 503:
                self.not_ready += 1
            elif status == 504:
                self.expired += 1
            elif status <= 0:
                self.transport += 1
            else:
                self.other_http += 1
            if tenant is not None:
                t = self.tenants.get(tenant)
                if t is None:
                    t = self.tenants[tenant] = {
                        "requests": 0, "ok": 0, "rejected_429": 0,
                        "other_errors": 0, "latencies": [],
                    }
                t["requests"] += 1
                if status == 200:
                    t["ok"] += 1
                    t["latencies"].append(latency_ms)
                elif status == 429:
                    t["rejected_429"] += 1
                else:
                    t["other_errors"] += 1

    def count_connection(self) -> None:
        with self.lock:
            self.connections_opened += 1

    def count_integrity(self, wrong: bool, mixed: bool) -> None:
        with self.lock:
            self.wrong_answers += int(wrong)
            self.mixed_iteration_answers += int(mixed)

    def count_degraded(self, wrong: bool) -> None:
        with self.lock:
            self.degraded += 1
            self.degraded_wrong += int(wrong)

    @property
    def total(self) -> int:
        return (self.ok + self.rejected + self.not_ready + self.expired
                + self.transport + self.other_http)


def _percentile(sorted_values: List[float], q: float) -> Optional[float]:
    if not sorted_values:
        return None
    i = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[i]


class _KeepAliveConn:
    """One worker's persistent HTTP connection: reused across requests,
    replaced on transport error or server close.  A connection that was
    *reused* and failed before yielding a response gets one fresh-
    connection retry (the server reaping an idle keep-alive socket is
    routine, not an error class)."""

    def __init__(self, url: str, timeout_s: float, stats: _Stats):
        u = urlparse(url)
        self._host = u.hostname
        self._port = u.port
        self._timeout = timeout_s
        self._stats = stats
        self._conn: Optional[http.client.HTTPConnection] = None
        self._fresh = True

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def request(self, method: str, path: str, body: Optional[bytes],
                headers: Dict[str, str]) -> Tuple[int, bytes]:
        for _attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
                self._fresh = True
                self._stats.count_connection()
            reused = not self._fresh
            try:
                self._conn.request(
                    method, path, body=body, headers=headers
                )
                resp = self._conn.getresponse()
                raw = resp.read()
                self._fresh = False
                if resp.will_close:
                    self.close()
                return resp.status, raw
            except (OSError, http.client.HTTPException):
                self.close()
                if not reused:
                    raise
                # stale keep-alive socket: one retry on a fresh dial
        raise OSError("unreachable")  # pragma: no cover


#: reserved key in the verify_ref dict carrying the sharded-fleet
#: verification context ({"ranges": {index: (start, end)}, "row":
#: {gene: global row}}); gene names can never collide with it
SHARD_CTX_KEY = "__shard__"


def _degraded_consistent(neighbors, ref_neighbors, shard_ctx,
                         answered) -> bool:
    """Whether a degraded answer is exactly what the reference implies
    for the shards that answered: every returned row lives on an
    answered shard, and the reference's surviving members lead the
    list IN ORDER (the restricted top-k starts with exactly the live
    members of the full top-k — anything else means the merge lost or
    invented answers)."""
    rows = shard_ctx.get("row", {})
    ranges = shard_ctx.get("ranges", {})
    live = [ranges[i] for i in answered if i in ranges]

    def on_live_shard(gene) -> bool:
        row = rows.get(gene)
        if row is None:
            return False
        return any(s <= row < e for s, e in live)

    if not all(on_live_shard(g) for g in neighbors):
        return False
    surviving = tuple(g for g in ref_neighbors if on_live_shard(g))
    lead = surviving[: len(neighbors)]
    return neighbors[: len(lead)] == lead


def _check_answer(raw: bytes, verify_ref: Dict, stats: _Stats) -> None:
    """Compare one 200 body against the pre-fetched reference.  A
    response flagged ``degraded`` (sharded fleet, partial gather) is
    scored against the reference restricted to the shards that
    answered — it is counted in the degraded columns, never as a
    wrong answer."""
    try:
        doc = json.loads(raw.decode("utf-8"))
        got_iter = doc["model"]["iteration"]
        res = doc["results"][0]
        gene = res["query"]
        neighbors = tuple(n["gene"] for n in res["neighbors"])
    except (ValueError, KeyError, IndexError, TypeError):
        stats.count_integrity(wrong=True, mixed=False)
        return
    ref = verify_ref.get(gene)
    if ref is None:
        stats.count_integrity(wrong=True, mixed=False)
        return
    ref_iter, ref_neighbors = ref
    if got_iter != ref_iter:
        stats.count_integrity(wrong=False, mixed=True)
        return
    if doc.get("degraded"):
        shard_ctx = verify_ref.get(SHARD_CTX_KEY)
        if not neighbors and res.get("degraded"):
            # honest empty partial (the query gene's owner is down and
            # its vector was never cached): degraded, nothing to score
            stats.count_degraded(wrong=False)
            return
        answered = (doc.get("shards") or {}).get("indexes") or []
        ok = shard_ctx is not None and _degraded_consistent(
            neighbors, ref_neighbors, shard_ctx, answered
        )
        stats.count_degraded(wrong=not ok)
        return
    if neighbors != ref_neighbors:
        stats.count_integrity(wrong=True, mixed=False)


def parse_tenants(specs: List[str]) -> Optional[List[Tuple[str, float]]]:
    """``--tenant id[:weight]`` flags -> [(id, cumulative_weight)] for
    weighted draws; None when tenancy is off."""
    if not specs:
        return None
    out: List[Tuple[str, float]] = []
    cum = 0.0
    for spec in specs:
        tid, sep, w = spec.partition(":")
        if not tid:
            raise ValueError(f"--tenant must be id[:weight], got {spec!r}")
        weight = float(w) if sep else 1.0
        if weight <= 0:
            raise ValueError(f"--tenant {spec!r}: weight must be > 0")
        cum += weight
        out.append((tid, cum))
    return out


def _pick_tenant(tenants: Optional[List[Tuple[str, float]]],
                 rng: random.Random) -> Optional[str]:
    if not tenants:
        return None
    r = rng.random() * tenants[-1][1]
    for tid, cum in tenants:
        if r <= cum:
            return tid
    return tenants[-1][0]


def _one_request(conn: Optional[_KeepAliveConn], url: str,
                 genes: List[str], k: int, rng: random.Random,
                 stats: _Stats, timeout_s: float,
                 client=None, trace: bool = False,
                 method: str = "post",
                 verify_ref: Optional[Dict] = None,
                 t_ref: Optional[float] = None,
                 tenants: Optional[List[Tuple[str, float]]] = None) -> None:
    gene = rng.choice(genes)
    tenant = _pick_tenant(tenants, rng)
    # when tracing, THIS request is a sampled trace root: the resilient
    # client adopts it as the ambient base (child span per attempt), the
    # plain path sends it as the traceparent header directly
    ctx = tracecontext.new_trace(sampled=True) if trace else None
    t0 = t_ref if t_ref is not None else time.monotonic()
    if client is not None:
        # the resilient path: retries/hedging under one deadline, with
        # per-request attempt accounting for the amplification report
        if method == "get":
            path, body = f"/v1/similar?gene={quote(gene)}&k={k}", None
        else:
            path, body = "/v1/similar", {"genes": [gene], "k": k}
        with tracecontext.use(ctx):
            r = client.request(
                path, body, timeout_s=timeout_s,
                headers={"X-Tenant": tenant} if tenant else None,
            )
        status = r.status
        if status == 0:
            # no HTTP status reached the caller: bucket the client's own
            # deadline exhaustion with the 504s, transport trouble apart
            status = 504 if r.error_class == "deadline" else -1
        if status == 200 and verify_ref is not None and r.raw:
            _check_answer(r.raw, verify_ref, stats)
        stats.record(
            status,
            (time.monotonic() - t0) * 1000.0,
            retries=r.retries, hedged=r.hedged, attempts=r.attempts,
            trace_id=r.trace_id if trace else None,
            tenant=tenant,
        )
        return
    assert conn is not None
    headers: Dict[str, str] = {}
    if ctx is not None:
        headers[TRACEPARENT_HEADER] = ctx.to_header()
    if tenant is not None:
        headers["X-Tenant"] = tenant
    try:
        if method == "get":
            status, raw = conn.request(
                "GET", f"/v1/similar?gene={quote(gene)}&k={k}", None,
                headers,
            )
        else:
            headers["Content-Type"] = "application/json"
            status, raw = conn.request(
                "POST", "/v1/similar",
                json.dumps({"genes": [gene], "k": k}).encode("utf-8"),
                headers,
            )
        if status == 200 and verify_ref is not None:
            _check_answer(raw, verify_ref, stats)
    except Exception:
        status = -1
    stats.record(
        status, (time.monotonic() - t0) * 1000.0,
        trace_id=ctx.trace_id if ctx is not None else None,
        tenant=tenant,
    )


def run_open_level(url: str, genes: List[str], k: int, rps: float,
                   duration_s: float, seed: int, timeout_s: float,
                   client=None, trace: bool = False,
                   method: str = "post", workers: int = 128,
                   verify_ref: Optional[Dict] = None,
                   tenants: Optional[List[Tuple[str, float]]] = None,
                   ) -> _Stats:
    """Fixed-schedule arrivals at ``rps`` for ``duration_s`` handed to
    a worker pool with persistent connections.  Latency is measured
    from each arrival's SCHEDULED time — a saturated pool shows up as
    latency, never as reduced offered load (that is what keeps the
    loop open)."""
    stats = _Stats()
    n = int(rps * duration_s)
    tasks: "queue_mod.Queue[Optional[float]]" = queue_mod.Queue()
    n_workers = max(1, min(workers, n))

    def work(widx: int) -> None:
        rng = random.Random(seed * 1000003 + widx)
        conn = _KeepAliveConn(url, timeout_s, stats)
        try:
            while True:
                target = tasks.get()
                if target is None:
                    return
                _one_request(
                    conn, url, genes, k, rng, stats, timeout_s, client,
                    trace, method, verify_ref, t_ref=target,
                    tenants=tenants,
                )
        finally:
            conn.close()

    threads = [
        threading.Thread(target=work, args=(w,), daemon=True)
        for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    interval = 1.0 / rps
    t_start = time.monotonic()
    for i in range(n):
        target = t_start + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        tasks.put(target)
    for _ in threads:
        tasks.put(None)
    for t in threads:
        t.join(timeout=timeout_s + 5.0)
    stats.wall_s = time.monotonic() - t_start  # type: ignore[attr-defined]
    return stats


def run_closed_level(url: str, genes: List[str], k: int, workers: int,
                     duration_s: float, seed: int,
                     timeout_s: float, client=None,
                     trace: bool = False, method: str = "post",
                     verify_ref: Optional[Dict] = None,
                     tenants: Optional[List[Tuple[str, float]]] = None,
                     ) -> _Stats:
    """N workers firing back-to-back on persistent connections until
    the clock runs out."""
    stats = _Stats()
    stop = time.monotonic() + duration_s

    def loop(worker_seed: int) -> None:
        rng = random.Random(worker_seed)
        conn = _KeepAliveConn(url, timeout_s, stats)
        try:
            while time.monotonic() < stop:
                _one_request(conn, url, genes, k, rng, stats, timeout_s,
                             client, trace, method, verify_ref,
                             tenants=tenants)
        finally:
            conn.close()

    t_start = time.monotonic()
    threads = [
        threading.Thread(target=loop, args=(seed + w,), daemon=True)
        for w in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + timeout_s + 5.0)
    stats.wall_s = time.monotonic() - t_start  # type: ignore[attr-defined]
    return stats


def summarize(level: float, stats: _Stats, mode: str,
              resilient: bool = False, trace_sample: int = 0,
              verify: bool = False) -> Dict:
    lat = sorted(stats.latencies_ms)
    wall = getattr(stats, "wall_s", 1.0) or 1.0
    row = {
        ("offered_rps" if mode == "open" else "concurrency"): level,
        "requests": stats.total,
        "ok": stats.ok,
        "rejected_429": stats.rejected,
        "not_ready_503": stats.not_ready,
        "expired_504": stats.expired,
        "transport_errors": stats.transport,
        "other_http_errors": stats.other_http,
        "availability": round(
            stats.ok / stats.total, 4
        ) if stats.total else None,
        "achieved_rps": round(stats.ok / wall, 2),
        "rejection_rate": round(
            stats.rejected / stats.total, 4
        ) if stats.total else None,
        "p50_ms": round(_percentile(lat, 0.50), 3) if lat else None,
        "p99_ms": round(_percentile(lat, 0.99), 3) if lat else None,
        "mean_ms": round(sum(lat) / len(lat), 3) if lat else None,
        "wall_s": round(wall, 3),
        "connections_opened": stats.connections_opened,
    }
    if resilient:
        row["retries"] = stats.retries
        row["hedges"] = stats.hedges
        row["attempts"] = stats.attempts
        row["attempt_amplification"] = round(
            stats.attempts / stats.total, 4
        ) if stats.total else None
    if verify:
        row["wrong_answers"] = stats.wrong_answers
        row["mixed_iteration_answers"] = stats.mixed_iteration_answers
        row["degraded"] = stats.degraded
        row["degraded_rate"] = round(
            stats.degraded / stats.total, 4
        ) if stats.total else None
        row["degraded_wrong"] = stats.degraded_wrong
    if stats.tenants:
        # per-tenant breakdown: isolation is invisible in the merged
        # row (the abuser's 429s and the victim's p99 cancel out)
        row["tenants"] = {}
        for tid in sorted(stats.tenants):
            t = stats.tenants[tid]
            lat_t = sorted(t["latencies"])
            row["tenants"][tid] = {
                "requests": t["requests"],
                "ok": t["ok"],
                "rejected_429": t["rejected_429"],
                "other_errors": t["other_errors"],
                "availability": round(
                    t["ok"] / t["requests"], 4
                ) if t["requests"] else None,
                "p50_ms": round(
                    _percentile(lat_t, 0.50), 3
                ) if lat_t else None,
                "p99_ms": round(
                    _percentile(lat_t, 0.99), 3
                ) if lat_t else None,
            }
    if trace_sample > 0 and stats.traces:
        # the N slowest requests, with the trace ids to go look at:
        # `python -m gene2vec_tpu.cli.obs trace <run_dir> <trace_id>`
        slowest = sorted(stats.traces, reverse=True)[:trace_sample]
        row["slowest_traces"] = [
            {"latency_ms": round(lat, 3), "status": status,
             "trace_id": tid}
            for lat, status, tid in slowest
        ]
    return row


def compute_capacity(rows: List[Dict], p99_budget_ms: float,
                     min_availability: float) -> Dict:
    """The capacity verdict over one phase's level rows: the highest
    offered level that SUSTAINED its load — availability and p99 within
    the criteria and achieved throughput >= 90% of offered (open mode;
    closed-mode rows qualify on the latency/availability criteria
    alone).  ``sustained_rps`` is 0 when no level qualified."""
    best: Optional[Dict] = None
    for row in rows:
        level = row.get("offered_rps")
        p99 = row.get("p99_ms")
        avail = row.get("availability")
        if p99 is None or avail is None:
            continue
        if p99 > p99_budget_ms or avail < min_availability:
            continue
        if level is not None and (
            (row.get("achieved_rps") or 0.0) < 0.9 * level
        ):
            continue
        rate = level if level is not None else row.get("achieved_rps")
        if best is None or rate > best["sustained_rps"]:
            best = {
                "sustained_rps": rate,
                "p99_ms": p99,
                "p50_ms": row.get("p50_ms"),
                "availability": avail,
            }
    out = best if best is not None else {
        "sustained_rps": 0.0, "p99_ms": None, "p50_ms": None,
        "availability": None,
    }
    out["p99_budget_ms"] = p99_budget_ms
    out["min_availability"] = min_availability
    return out


def run_batch_phase(url: str, level: float, run_level, mode: str,
                    args) -> Dict:
    """Mixed-workload phase (docs/BATCH.md#slo-protection): one
    baseline interactive window, then the SAME window again while a
    ``knn_graph`` batch job runs in the front door's background-
    priority lane.  Reports the interactive p99 delta — the number the
    batch pacer exists to keep small — next to the batch lane's goodput
    over the overlap window, so both sides of the priority trade land
    in one record (``analysis/passes_batch.py`` gates the delta).

    Needs the target to expose ``/v1/jobs`` (started with
    ``--jobs-dir``; ``--spawn`` targets get a temporary one
    automatically)."""
    print(f"batch phase: baseline window level {level:g} ...",
          file=sys.stderr)
    base = summarize(level, run_level(level, False), mode)
    job_id = f"loadgen-mixed-{os.getpid()}-{int(time.time())}"
    doc = _http_json(
        f"{url}/v1/jobs",
        {"type": "knn_graph", "k": args.batch_k,
         "chunk_rows": args.batch_chunk_rows, "job_id": job_id},
        timeout=args.timeout,
    )
    deadline = time.monotonic() + args.timeout
    while doc.get("state") == "pending":
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"batch job {job_id} never left 'pending'"
            )
        time.sleep(0.05)
        doc = _http_json(f"{url}/v1/jobs/{job_id}",
                         timeout=args.timeout)
    records_start = int(doc.get("records_done") or 0)
    t0 = time.monotonic()
    print(f"batch phase: window under job {job_id} ...",
          file=sys.stderr)
    under = summarize(level, run_level(level, False), mode)
    window_s = time.monotonic() - t0
    doc = _http_json(f"{url}/v1/jobs/{job_id}", timeout=args.timeout)
    records_end = int(doc.get("records_done") or 0)
    finished_early = doc.get("state") != "running"
    if not finished_early:
        try:
            _http_json(f"{url}/v1/jobs/{job_id}/cancel", {},
                       timeout=args.timeout)
        except urllib.error.HTTPError as e:
            e.close()  # raced to completion: 409, nothing to clean up
    p99_b, p99_u = base.get("p99_ms"), under.get("p99_ms")
    out = {
        "level": level,
        "mode": mode,
        "baseline": base,
        "under_batch": under,
        "interactive_p99_baseline_ms": p99_b,
        "interactive_p99_under_batch_ms": p99_u,
        "p99_delta_ms": (
            round(p99_u - p99_b, 3)
            if p99_b is not None and p99_u is not None else None
        ),
        "p99_delta_frac": (
            round((p99_u - p99_b) / p99_b, 4)
            if p99_b and p99_u is not None else None
        ),
        "batch": {
            "job_id": job_id,
            "type": "knn_graph",
            "k": args.batch_k,
            "chunk_rows": args.batch_chunk_rows,
            "state_after_window": doc.get("state"),
            "records_start": records_start,
            "records_end": records_end,
            "window_s": round(window_s, 3),
            "goodput_rows_per_sec": round(
                (records_end - records_start) / window_s, 2
            ) if window_s > 0 else None,
            "finished_early": finished_early,
            "result": doc.get("result"),
        },
    }
    print(f"batch mixed: p99 {p99_b} -> {p99_u} ms, goodput "
          f"{out['batch']['goodput_rows_per_sec']} rows/s "
          f"({records_end - records_start} records in "
          f"{window_s:.1f}s)", file=sys.stderr)
    return out


def fetch_verify_ref(url: str, genes: List[str], k: int,
                     timeout_s: float) -> Dict:
    """One reference answer per query gene, fetched BEFORE the load
    phase: (iteration, neighbor-gene tuple) keyed by gene.  Every 200
    response during the run must match — a mismatch is a wrong answer,
    a different iteration a mixed-iteration answer (no swaps happen
    during a bench)."""
    ref: Dict = {}
    for gene in genes:
        doc = _http_json(
            f"{url}/v1/similar",
            {"genes": [gene], "k": k},
            timeout=timeout_s,
        )
        if doc.get("degraded"):
            raise RuntimeError(
                f"reference answer for {gene!r} came back DEGRADED — "
                "the sharded fleet is already partial; a bench "
                "baseline needs every shard up"
            )
        ref[gene] = (
            doc["model"]["iteration"],
            tuple(
                n["gene"] for n in doc["results"][0]["neighbors"]
            ),
        )
    return ref


def parse_shard_grid(health: Dict):
    """The (shard, replica) grid from a sharded front door's /healthz:
    ``(ranges, replicas)`` — per-shard row ranges plus each shard's
    replica-group size (``replicas: [{up, epoch}]`` per shard entry; a
    pre-grid fleet without the key reads as one replica per shard).
    None for an unsharded target."""
    shards = health.get("shards")
    if not isinstance(shards, list) or not shards:
        return None
    ranges = {
        int(s["index"]): tuple(s["rows"])
        for s in shards if s.get("rows")
    }
    replicas = {
        int(s["index"]): (
            len(s["replicas"]) if isinstance(s.get("replicas"), list)
            else 1
        )
        for s in shards
    }
    return ranges, replicas


def fetch_shard_ctx(url: str, health: Dict, timeout_s: float):
    """Degraded-answer verification context from a SHARDED front door:
    the (shard, replica) grid from /healthz plus the gene→global-row
    map implied by /v1/genes order (vocab order IS row order).  None
    for an unsharded target — verification then never consults it.
    Degraded scoring restricts the reference by SHARD (the unit of row
    coverage), never by replica — any live sibling serves the same
    rows, so which cell answered is irrelevant to correctness."""
    grid = parse_shard_grid(health)
    if grid is None:
        return None
    ranges, replicas = grid
    doc = _http_json(f"{url}/v1/genes?limit=1", timeout=timeout_s)
    total = int(doc["total"])
    rows: Dict[str, int] = {}
    offset = 0
    while offset < total:
        page = _http_json(
            f"{url}/v1/genes?limit=4096&offset={offset}",
            timeout=timeout_s,
        )["genes"]
        if not page:
            break
        for i, g in enumerate(page):
            rows[g] = offset + i
        offset += len(page)
    return {"ranges": ranges, "row": rows, "replicas": replicas}


def spawn_server(export_dir: str, extra: List[str]) -> "tuple":
    """Launch ``python -m gene2vec_tpu.cli.serve`` and parse its one
    stdout JSON status line for the bound URL."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "gene2vec_tpu.cli.serve",
         "--export-dir", export_dir, "--port", "0", *extra],
        stdout=subprocess.PIPE,
        stderr=sys.stderr,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise RuntimeError(
            f"serve CLI exited rc={proc.returncode} before reporting a URL"
        )
    info = json.loads(line)
    return proc, info


def spawn_fleet(export_dir: str, replicas: int,
                extra: List[str]) -> "tuple":
    """Launch ``python -m gene2vec_tpu.cli.fleet`` (N replicas + the
    front-door proxy) and parse its contract line."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "gene2vec_tpu.cli.fleet",
         "--export-dir", export_dir, "--replicas", str(replicas),
         "--port", "0", *extra],
        stdout=subprocess.PIPE,
        stderr=sys.stderr,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise RuntimeError(
            f"fleet CLI exited rc={proc.returncode} before reporting a URL"
        )
    info = json.loads(line)
    return proc, info


def _terminate(proc) -> None:
    if proc is None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _warmup(url: str, genes: List[str], k: int, rng: random.Random,
            timeout_s: float, warmup: int, client=None,
            method: str = "post") -> None:
    """Concurrent bursts of 1,2,4,...,N so the batcher forms each batch
    bucket and jit compiles land before the first measured level."""
    burst = 1
    while burst <= max(1, warmup):
        stats = _Stats()
        conns = [
            _KeepAliveConn(url, timeout_s, stats) for _ in range(burst)
        ]
        threads = [
            threading.Thread(
                target=_one_request,
                args=(conns[i], url, genes, k, rng, stats, timeout_s,
                      client, False, method),
                daemon=True,
            )
            for i in range(burst)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout_s + 5.0)
        for c in conns:
            c.close()
        burst *= 2


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_loadgen",
        description="Closed/open-loop load generator for the serve API.",
    )
    ap.add_argument("--url", default=None, help="target server base URL")
    ap.add_argument("--spawn", default=None, metavar="EXPORT_DIR",
                    help="spawn cli.serve on this export dir instead of "
                         "--url")
    ap.add_argument("--spawn-arg", action="append", default=[],
                    help="extra flag passed through to the spawned "
                         "cli.serve (repeatable)")
    ap.add_argument("--mode", choices=("open", "closed"), default="open")
    ap.add_argument("--levels", default="50,200,800",
                    help="comma-separated offered rps (open) or worker "
                         "counts (closed)")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per level")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--method", choices=("post", "get"), default="post",
                    help="post = full dispatch pipeline; get = the "
                         "event-loop hot read path (response cache + "
                         "coalescing)")
    ap.add_argument("--num-genes", type=int, default=256,
                    help="distinct query genes sampled from /v1/genes")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="client-side socket timeout (s)")
    ap.add_argument("--open-workers", type=int, default=128,
                    help="sender pool size for --mode open (each worker "
                         "holds one persistent connection)")
    ap.add_argument("--resilient", action="store_true",
                    help="route through gene2vec_tpu.serve.client."
                         "ResilientClient (retries + breakers; reports "
                         "retry/hedge counts per level)")
    ap.add_argument("--retries", type=int, default=3,
                    help="resilient client max attempts per request")
    ap.add_argument("--hedge", action="store_true",
                    help="enable p95 hedging on the resilient client")
    ap.add_argument("--verify", action="store_true",
                    help="pre-fetch a reference answer per gene and "
                         "check every 200 response against it "
                         "(wrong/mixed-iteration answer counts)")
    ap.add_argument("--tenant", action="append", default=[],
                    metavar="ID[:WEIGHT]",
                    help="emit mixed-tenant traffic: each request "
                         "draws a tenant id by WEIGHT (default 1) and "
                         "carries it as X-Tenant; every level row "
                         "gains a per-tenant requests/ok/429/"
                         "availability/p50/p99 breakdown (repeatable; "
                         "docs/SERVING.md#multi-tenant-admission)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="after the single-replica levels, spawn an "
                         "N-replica cli.fleet over the SAME export dir "
                         "and run --fleet-levels through its front "
                         "door (requires --spawn)")
    ap.add_argument("--fleet-levels", default=None,
                    help="comma-separated levels for the fleet phase "
                         "(default: --levels)")
    ap.add_argument("--fleet-arg", action="append", default=[],
                    help="extra flag for the spawned cli.fleet "
                         "(repeatable)")
    ap.add_argument("--capacity-p99-ms", type=float, default=50.0,
                    help="p99 criterion for the capacity verdict")
    ap.add_argument("--capacity-availability", type=float, default=0.99,
                    help="availability criterion for the capacity "
                         "verdict")
    ap.add_argument("--assert-capacity", type=float, default=None,
                    metavar="RPS",
                    help="exit 1 unless capacity.sustained_rps >= RPS "
                         "(CI smoke gate)")
    ap.add_argument("--assert-fleet-capacity", type=float, default=None,
                    metavar="RPS",
                    help="exit 1 unless fleet_capacity.sustained_rps "
                         ">= RPS")
    ap.add_argument("--batch-phase", action="store_true",
                    help="after the main levels, measure the mixed "
                         "workload: one baseline interactive window, "
                         "then the same window while a knn_graph batch "
                         "job runs in the background lane; reports the "
                         "interactive p99 delta and batch goodput "
                         "(docs/BATCH.md#slo-protection; --spawn "
                         "targets get a temporary --jobs-dir "
                         "automatically)")
    ap.add_argument("--batch-level", type=float, default=None,
                    help="interactive level for --batch-phase "
                         "(default: first --levels entry)")
    ap.add_argument("--batch-k", type=int, default=10,
                    help="neighbors per row for the --batch-phase job")
    ap.add_argument("--batch-chunk-rows", type=int, default=64,
                    help="records per committed chunk for the "
                         "--batch-phase job (small chunks yield to the "
                         "interactive lane often)")
    ap.add_argument("--trace-sample", type=int, default=0, metavar="N",
                    help="send a sampled traceparent root on EVERY "
                         "request and report the N slowest requests' "
                         "trace ids per level")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="measure traced-vs-untraced p50 at ONE level "
                         "(interleaved arms; emits the trace_overhead "
                         "section analysis/passes_obs.py gates)")
    ap.add_argument("--overhead-rounds", type=int, default=3,
                    help="untraced/traced round pairs for "
                         "--trace-overhead")
    ap.add_argument("--warm-window", type=float, default=2.0,
                    metavar="SECONDS",
                    help="discarded load window at the first level "
                         "before measurement (response caches + per-"
                         "replica jit warm up; 0 disables)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--warmup", type=int, default=64,
                    help="largest warm-up burst; concurrent bursts of "
                         "1,2,4,...,N give the batcher a chance to form "
                         "each batch bucket so jit compiles land before "
                         "the first measured level")
    ap.add_argument("--output", default="BENCH_SERVE_r11.json")
    args = ap.parse_args(argv)
    if (args.url is None) == (args.spawn is None):
        print("error: provide exactly one of --url / --spawn",
              file=sys.stderr)
        return 2
    if args.fleet and args.spawn is None:
        print("error: --fleet needs --spawn (the fleet serves the same "
              "export dir)", file=sys.stderr)
        return 2

    proc = None
    fleet_proc = None
    try:
        if args.spawn is not None:
            spawn_extra = list(args.spawn_arg)
            if args.batch_phase and not any(
                a.startswith("--jobs-dir") for a in spawn_extra
            ):
                spawn_extra += [
                    "--jobs-dir",
                    tempfile.mkdtemp(prefix="loadgen_jobs_"),
                ]
            proc, info = spawn_server(args.spawn, spawn_extra)
            url = info["url"]
            print(f"spawned serve at {url} (iteration "
                  f"{info['iteration']})", file=sys.stderr)
        else:
            url = args.url.rstrip("/")

        try:
            health = _http_json(f"{url}/healthz", timeout=args.timeout)
        except urllib.error.HTTPError as e:
            # readiness probes 503 until a model is served (or a fleet
            # has a replica in rotation) — report it, don't traceback
            print(
                f"error: {url}/healthz returned {e.code} — the server "
                "is not ready (no model loaded / no replica in rotation)",
                file=sys.stderr,
            )
            e.close()
            return 2
        genes_doc = _http_json(
            f"{url}/v1/genes?limit={args.num_genes}", timeout=args.timeout
        )
        genes = genes_doc["genes"]
        if not genes:
            print("error: server reports an empty vocab", file=sys.stderr)
            return 2

        client = None
        if args.resilient:
            from gene2vec_tpu.serve.client import (
                ResilientClient,
                RetryPolicy,
            )

            client = ResilientClient(
                [url],
                RetryPolicy(
                    max_attempts=args.retries,
                    read_timeout_s=args.timeout,
                    default_timeout_s=args.timeout,
                    hedge=args.hedge,
                ),
                rng=random.Random(args.seed),
            )

        try:
            tenants = parse_tenants(args.tenant)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

        rng = random.Random(args.seed)
        _warmup(url, genes, args.k, rng, args.timeout, args.warmup,
                client, args.method)
        verify_ref = None
        if args.verify:
            print(f"fetching {len(genes)} reference answers ...",
                  file=sys.stderr)
            verify_ref = fetch_verify_ref(url, genes, args.k,
                                          args.timeout)
            shard_ctx = fetch_shard_ctx(url, health, args.timeout)
            if shard_ctx is not None:
                # sharded front door: degraded answers get scored
                # against the reference restricted to live shards
                verify_ref[SHARD_CTX_KEY] = shard_ctx
                grid = shard_ctx.get("replicas") or {}
                print(
                    f"sharded target: {len(shard_ctx['ranges'])} "
                    "shards x "
                    f"{max(grid.values()) if grid else 1} replicas; "
                    "degraded answers verified against the reference "
                    "restricted by SHARD",
                    file=sys.stderr,
                )

        levels = [float(x) for x in args.levels.split(",") if x]
        trace_all = args.trace_sample > 0

        def run_level(level: float, trace: bool,
                      target_url: str = url,
                      ref: Optional[Dict] = None,
                      duration: Optional[float] = None,
                      level_client=None) -> _Stats:
            # the resilient client is bound to ONE base URL: the fleet
            # phase must pass its own client or the "fleet" numbers
            # would silently measure the single replica
            use_client = (
                level_client if level_client is not None
                else (client if target_url == url else None)
            )
            dur = duration if duration is not None else args.duration
            if args.mode == "open":
                return run_open_level(
                    target_url, genes, args.k, level, dur,
                    args.seed, args.timeout, use_client, trace=trace,
                    method=args.method, workers=args.open_workers,
                    verify_ref=ref, tenants=tenants,
                )
            return run_closed_level(
                target_url, genes, args.k, int(level), dur,
                args.seed, args.timeout, use_client, trace=trace,
                method=args.method, verify_ref=ref, tenants=tenants,
            )

        def warm_window(level: float, target_url: str,
                        level_client=None) -> None:
            """One discarded window: per-replica response caches and
            jit programs warm up OFF the record, so the first measured
            level reports steady state, not cold start."""
            if args.warm_window <= 0:
                return
            print(f"warm window level {level:g} for "
                  f"{args.warm_window:g}s ...", file=sys.stderr)
            run_level(level, False, target_url=target_url,
                      duration=args.warm_window,
                      level_client=level_client)

        results = []
        overhead = None
        if args.trace_overhead:
            if len(levels) != 1:
                print("error: --trace-overhead needs exactly one level "
                      "(the budgeted rps)", file=sys.stderr)
                return 2
            level = levels[0]
            # one discarded window at the measured level first: the
            # burst warmup above compiles the small batch buckets, but
            # the first sustained window still pays cold caches, and
            # whichever arm ran first would eat that bias
            print(f"overhead warm window level {level:g} ...",
                  file=sys.stderr)
            run_level(level, False)
            # per-arm estimate = MEDIAN of per-window p50s, arms
            # alternating order each round: this host's window p50s
            # swing several-x between identical windows (a single
            # melted window poisons pooled latencies), and the median
            # over windows shrugs off the outliers both arms suffer
            window_p50s = {False: [], True: []}
            n_per_arm = {False: 0, True: 0}
            for rnd in range(max(1, args.overhead_rounds)):
                order = (False, True) if rnd % 2 == 0 else (True, False)
                for traced in order:
                    arm = "traced" if traced else "untraced"
                    print(f"overhead round {rnd + 1}/"
                          f"{args.overhead_rounds} {arm} level "
                          f"{level:g} ...", file=sys.stderr)
                    stats = run_level(level, traced)
                    w50 = _percentile(sorted(stats.latencies_ms), 0.50)
                    if w50 is not None:
                        window_p50s[traced].append(w50)
                        n_per_arm[traced] += len(stats.latencies_ms)
                    row = summarize(level, stats, args.mode,
                                    args.resilient)
                    row["arm"] = arm
                    row["round"] = rnd + 1
                    results.append(row)

            p50_u = _percentile(sorted(window_p50s[False]), 0.50)
            p50_t = _percentile(sorted(window_p50s[True]), 0.50)
            if not p50_u or p50_t is None:
                print("error: no successful requests in an arm — "
                      "overhead is unmeasurable", file=sys.stderr)
                return 2
            overhead = {
                "rps": level,
                "mode": args.mode,
                "duration_s": args.duration,
                "rounds": args.overhead_rounds,
                "n_untraced": n_per_arm[False],
                "n_traced": n_per_arm[True],
                "window_p50s_untraced_ms": [
                    round(v, 3) for v in window_p50s[False]
                ],
                "window_p50s_traced_ms": [
                    round(v, 3) for v in window_p50s[True]
                ],
                "p50_untraced_ms": round(p50_u, 3),
                "p50_traced_ms": round(p50_t, 3),
                "regression_frac": round((p50_t - p50_u) / p50_u, 4),
            }
            print(f"trace overhead: {json.dumps(overhead)}",
                  file=sys.stderr)
        else:
            warm_window(levels[0], url)
            for level in levels:
                print(f"level {level:g} ({args.mode}, {args.method}) "
                      f"for {args.duration:g}s ...", file=sys.stderr)
                stats = run_level(level, trace_all, ref=verify_ref)
                row = summarize(level, stats, args.mode, args.resilient,
                                trace_sample=args.trace_sample,
                                verify=args.verify)
                print(f"  -> {json.dumps(row)}", file=sys.stderr)
                results.append(row)

        capacity = None
        if not args.trace_overhead and args.mode == "open":
            capacity = compute_capacity(
                results, args.capacity_p99_ms, args.capacity_availability
            )
            print(f"capacity: {json.dumps(capacity)}", file=sys.stderr)

        batch_mixed = None
        if args.batch_phase and not args.trace_overhead:
            batch_mixed = run_batch_phase(
                url,
                args.batch_level if args.batch_level is not None
                else levels[0],
                run_level, args.mode, args,
            )

        fleet_results = None
        fleet_capacity = None
        fleet_info = None
        if args.fleet:
            fleet_proc, fleet_info = spawn_fleet(
                args.spawn, args.fleet, args.fleet_arg
            )
            fleet_url = fleet_info["url"]
            print(f"spawned {args.fleet}-replica fleet at {fleet_url}",
                  file=sys.stderr)
            _warmup(fleet_url, genes, args.k, rng, args.timeout,
                    args.warmup, None, args.method)
            fleet_ref = (
                fetch_verify_ref(fleet_url, genes, args.k, args.timeout)
                if args.verify else None
            )
            fleet_client = None
            if args.resilient:
                from gene2vec_tpu.serve.client import (
                    ResilientClient,
                    RetryPolicy,
                )

                fleet_client = ResilientClient(
                    [fleet_url],
                    RetryPolicy(
                        max_attempts=args.retries,
                        read_timeout_s=args.timeout,
                        default_timeout_s=args.timeout,
                        hedge=args.hedge,
                    ),
                    rng=random.Random(args.seed),
                )
            fleet_levels = [
                float(x)
                for x in (args.fleet_levels or args.levels).split(",")
                if x
            ]
            fleet_results = []
            warm_window(fleet_levels[0], fleet_url,
                        level_client=fleet_client)
            for level in fleet_levels:
                print(f"fleet level {level:g} ({args.mode}, "
                      f"{args.method}) for {args.duration:g}s ...",
                      file=sys.stderr)
                stats = run_level(level, trace_all,
                                  target_url=fleet_url, ref=fleet_ref,
                                  level_client=fleet_client)
                row = summarize(level, stats, args.mode, args.resilient,
                                trace_sample=args.trace_sample,
                                verify=args.verify)
                print(f"  -> {json.dumps(row)}", file=sys.stderr)
                fleet_results.append(row)
            if args.mode == "open":
                fleet_capacity = compute_capacity(
                    fleet_results, args.capacity_p99_ms,
                    args.capacity_availability,
                )
                print(f"fleet capacity: {json.dumps(fleet_capacity)}",
                      file=sys.stderr)

        doc = {
            # provenance stamp (ledger contract, docs/BENCHMARKS.md):
            # adapters treat records without schema_version as legacy
            "schema_version": 2,
            "command": " ".join([sys.executable, *sys.argv]),
            "created_unix": time.time(),
            "bench": ("trace_overhead" if args.trace_overhead
                      else "serve_loadgen"),
            "mode": args.mode,
            "method": args.method,
            "k": args.k,
            "duration_s": args.duration,
            "num_query_genes": len(genes),
            "open_workers": args.open_workers,
            "warm_window_s": args.warm_window,
            "server": health.get("model", {}),
            "resilient": bool(args.resilient),
            "verify": bool(args.verify),
            "tenants": args.tenant or None,
            "trace_sample": args.trace_sample,
            "levels": results,
        }
        if capacity is not None:
            doc["capacity"] = capacity
        if batch_mixed is not None:
            doc["batch_mixed"] = batch_mixed
        if fleet_results is not None:
            doc["fleet_replicas"] = args.fleet
            doc["fleet_levels"] = fleet_results
            if fleet_capacity is not None:
                doc["fleet_capacity"] = fleet_capacity
            if fleet_client is not None:
                doc["fleet_client_stats"] = dict(fleet_client.stats)
        if overhead is not None:
            doc["trace_overhead"] = overhead
        if client is not None:
            doc["client_stats"] = dict(client.stats)
            transport = getattr(client, "_transport", None)
            opened = getattr(transport, "connections_opened", None)
            if opened is not None:
                doc["client_stats"]["connections_opened"] = opened
                doc["client_stats"]["stale_retries"] = (
                    transport.stale_retries
                )
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        # the one stdout line is the product; chatter above is stderr
        print(json.dumps(doc), file=sys.stdout)
        rc = 0
        if args.assert_capacity is not None:
            got = (capacity or {}).get("sustained_rps") or 0.0
            if got < args.assert_capacity:
                print(f"CAPACITY ASSERT FAILED: sustained {got:g} rps "
                      f"< required {args.assert_capacity:g}",
                      file=sys.stderr)
                rc = 1
        if args.assert_fleet_capacity is not None:
            got = (fleet_capacity or {}).get("sustained_rps") or 0.0
            if got < args.assert_fleet_capacity:
                print(f"FLEET CAPACITY ASSERT FAILED: sustained "
                      f"{got:g} rps < required "
                      f"{args.assert_fleet_capacity:g}", file=sys.stderr)
                rc = 1
        return rc
    finally:
        _terminate(fleet_proc)
        _terminate(proc)


if __name__ == "__main__":
    sys.exit(main())
